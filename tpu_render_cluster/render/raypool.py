"""Device-resident ray pool: cross-frame wavefront batching, in-jit
compaction, zero host syncs in the steady state.

The PR-2 wavefront driver (render/compaction.py) buys shrinking launch
widths with ONE DEVICE SYNC PER BOUNCE, and its launch width can only
shrink — dead lanes are reclaimed in block-sized buckets but never
refilled, so on the deep-walk scenes it exists for it still measured
73.7% wasted lanes and a 1.05x win (results/WAVEFRONT_BENCH.json). The
wavefront literature's fix ("Megakernels Considered Harmful": keep a
persistent ray queue saturated; "Data Parallel Path Tracing in Object
Space": decouple the work unit from the image) is to make the pool
DEVICE-RESIDENT and CONTINUOUSLY REFILLED: lanes freed by frame i's
dead paths are immediately reloaded with frame i+1's next unserved
primary rays, so the kernel never drains and the host never syncs
mid-batch.

Execution shape: ONE jitted program per (scene family, frame-window
cap, image config, pool width) runs a ``lax.while_loop`` over a
fixed-width pool. Each iteration, entirely on device:

1. permutation — dead lanes to the tail; for mesh scenes the
   coherence re-sort (frame id, candidate instance, Morton cell,
   direction octant) FOLDS INTO the same permutation (one argsort key
   with a dead bit, the pool generalization of integrator
   ``_ray_sort_order``); sphere scenes need no coherence and reuse
   ``compaction.compaction_order``'s prefix-sum partition;
2. refill — freed tail slots gather the next unserved primary rays of
   the multi-frame batch (pre-generated in the same program via the
   shared ``integrator.flat_sample_rays`` derivation, so rays and RNG
   provably match the masked per-frame renderer);
3. bounce — ONE pool-mode kernel launch (``pallas_kernels.pool_io``):
   lanes carry ``(frame, original_lane, bounce)`` so the counter PCG
   streams are bit-identical to the masked loop's, and the stacked
   multi-frame scene is masked per lane by frame id;
4. scatter-back — each lane's contribution lands in its own frame's
   buffer at ``frame * rays_per_frame + lane`` regardless of service
   order.

The loop condition (`unserved primaries remain or any lane alive`) and
everything above are device arithmetic: the host blocks exactly once,
at the end of the batch, to fetch the finished frames — one sync per
BATCH instead of one per bounce.

Per-iteration occupancy/refill telemetry is accumulated in fixed-size
device logs carried through the loop and emitted AFTER the batch:
``render_pool_occupancy`` gauge, ``render_pool_live_fraction``
histogram (bench.py's raypool wasted_lane_fraction), refill/iteration
counters, and per-iteration Perfetto spans on a dedicated "raypool"
track. Span timing within a batch is synthetic (the batch wall time
split evenly — the device never told the host when iterations
happened; that is the point), flagged ``synthetic_timing`` in args;
occupancy/refill args are real device-measured values.
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp

from tpu_render_cluster.utils.env import env_int, env_str
from tpu_render_cluster.render import pallas_kernels as pk
from tpu_render_cluster.render.compaction import (
    ALIVE_FRACTION_BUCKETS,
    compaction_order,
    note_compile,
)

# Fixed length of the per-iteration device telemetry logs carried through
# the while loop. Batches that somehow exceed it keep rendering correctly
# (late iterations overwrite the last slot); only telemetry truncates, and
# the emitter flags it.
RAYPOOL_LOG_CAP = 2048

# Hard ceiling on the frame-window cap (the sort key holds 5 frame-id
# bits, and the stacked-scene sweep cost grows with the window).
RAYPOOL_MAX_FRAMES = 32


def raypool_mode() -> str:
    """The ``TRC_RAYPOOL`` env tier: ``off`` / ``auto`` / ``force``.

    - unset (``auto``): the pool driver is used where it pays — multi-
      frame batches of deep-walk mesh scenes (the wavefront-eligible
      set, which is exactly where masked dead lanes still fund BVH
      packet walks);
    - ``TRC_RAYPOOL=0`` (also ``false``/``off``/``no``): never;
    - anything else truthy: force it for every Pallas-rendered scene,
      single frames and spheres included.
    """
    value = (env_str("TRC_RAYPOOL") or "").strip().lower()
    if value in ("", "auto"):
        return "auto"
    if value in ("0", "false", "off", "no"):
        return "off"
    return "force"


def raypool_frame_cap() -> int:
    """Frames per compiled pool window (``TRC_RAYPOOL_FRAMES``, default 8).

    A COMPILE-TIME capacity, not the batch size: any batch of 1..cap
    frames reuses the same compiled program (the served-ray total is a
    traced scalar), and larger batches chunk into windows of this size
    (one host sync per window). Clamped to [1, RAYPOOL_MAX_FRAMES].
    """
    cap = env_int("TRC_RAYPOOL_FRAMES", 8)
    return max(1, min(cap, RAYPOOL_MAX_FRAMES))


def raypool_width(rays_per_frame: int, block: int) -> int:
    """Pool width: ``TRC_RAYPOOL_WIDTH`` or one frame's rays, block-
    rounded and clamped to [1, 64] blocks. Part of the pool config (a
    distinct width is a distinct compile), independent of batch size."""
    width = env_int(
        "TRC_RAYPOOL_WIDTH", min(rays_per_frame, 64 * block)
    )
    return max(block, -(-width // block) * block)


def raypool_active(
    scene_name: str,
    *,
    backend_flag: str | None = None,
    frames_ahead: int = 0,
    frame=1,
) -> bool:
    """Whether the ray-pool driver should render this workload.

    ``backend_flag`` (the worker's ``--raypool`` / constructor knob)
    overrides the ``TRC_RAYPOOL`` env tier; ``auto`` selects multi-frame
    deep-walk mesh jobs (``frames_ahead`` >= 1 more frames queued beyond
    the current one, scene in the wavefront-eligible set) — single-frame
    work keeps the per-frame dispatch, where the pool cannot refill
    across frames and degenerates into the wavefront driver minus its
    shrinking launches.
    """
    if not pk.pallas_enabled():
        return False
    mode = backend_flag if backend_flag is not None else raypool_mode()
    mode = str(mode).lower()
    if mode in ("0", "false", "off", "no"):
        return False
    if mode not in ("auto", ""):
        return True
    if frames_ahead < 1:
        return False
    from tpu_render_cluster.render.mesh import scene_mesh_set

    return pk.wavefront_eligible(scene_mesh_set(scene_name, frame))


# -- obs ---------------------------------------------------------------------


def pool_occupancy_gauge(registry=None):
    """Mean live-lane fraction of the pool over the last batch."""
    from tpu_render_cluster.obs import get_registry

    registry = registry if registry is not None else get_registry()
    return registry.gauge(
        "render_pool_occupancy",
        "Mean live fraction of the ray pool across the last batch's "
        "iterations (live lanes / pool width)",
    )


def pool_live_fraction_histogram(registry=None):
    """Per-iteration pool live fraction (1 - mean = wasted lanes)."""
    from tpu_render_cluster.obs import get_registry

    registry = registry if registry is not None else get_registry()
    return registry.histogram(
        "render_pool_live_fraction",
        "Per-iteration live fraction of the LAUNCHED pool width (live "
        "prefix rounded up to whole blocks; skipped all-dead tail "
        "blocks don't count — the same basis as the wavefront driver's "
        "live/bucket). 1 - this, averaged, is the raypool "
        "wasted_lane_fraction",
        buckets=ALIVE_FRACTION_BUCKETS,
    )


def pool_refill_counter(registry=None):
    from tpu_render_cluster.obs import get_registry

    registry = registry if registry is not None else get_registry()
    return registry.counter(
        "render_pool_refill_rays_total",
        "Primary rays loaded into freed pool lanes (the cross-frame "
        "refill the ray pool exists for)",
    )


def pool_launched_lanes_counter(registry=None):
    from tpu_render_cluster.obs import get_registry

    registry = registry if registry is not None else get_registry()
    return registry.counter(
        "render_pool_launched_lanes_total",
        "Pool lanes launched (live prefix rounded up to whole blocks, "
        "summed over iterations) — the denominator of the lane-weighted "
        "raypool wasted_lane_fraction",
    )


def pool_live_lanes_counter(registry=None):
    from tpu_render_cluster.obs import get_registry

    registry = registry if registry is not None else get_registry()
    return registry.counter(
        "render_pool_live_lanes_total",
        "Live lanes at launch, summed over iterations — the numerator "
        "of the lane-weighted raypool occupancy",
    )


def pool_iteration_counter(registry=None):
    from tpu_render_cluster.obs import get_registry

    registry = registry if registry is not None else get_registry()
    return registry.counter(
        "render_pool_iterations_total",
        "Ray-pool while-loop iterations (one fused "
        "sort+refill+bounce+scatter step per iteration, no host sync)",
    )


def raypool_wasted_lane_fraction(registry=None) -> float | None:
    """Lane-weighted: total dead launched lanes / total launched lanes.

    The raypool analog of compaction.wasted_lane_fraction — the actual
    fraction of launched pool lanes that carried no live ray, aggregated
    over every iteration of every batch. Lane-weighted (counter-based),
    NOT a mean of per-iteration ratios: the drain tail's tiny launches
    have big ratios but near-zero cost, and must not dominate the
    record. None before any pool batch ran.
    """
    launched = pool_launched_lanes_counter(registry).value()
    if launched <= 0:
        return None
    return 1.0 - pool_live_lanes_counter(registry).value() / launched


# -- the device program ------------------------------------------------------


def _dilate4(v):
    """Spread a 4-bit value to every 3rd bit (Morton dilation, readable
    bit-by-bit form — only 4 bits, so cleverness buys nothing)."""
    return (
        ((v >> 0) & jnp.uint32(1))
        | (((v >> 1) & jnp.uint32(1)) << 3)
        | (((v >> 2) & jnp.uint32(1)) << 6)
        | (((v >> 3) & jnp.uint32(1)) << 9)
    )


def _pool_sort_order(origins, directions, alive, fid, lo_w, hi_w):
    """One permutation = compaction AND coherence for the mesh pool.

    Key layout (LSB→MSB): direction octant [0:3), Morton cell of
    origin+direction [3:15), candidate instance [15:25), frame id
    [25:30), dead flag bit 30. Dead lanes sort to the tail (the live-
    count block-skip contract); live lanes group by frame FIRST — a
    frame-pure block top-level-culls every other frame's instances —
    then pack into candidate/Morton-coherent packets exactly like the
    integrator's per-bounce re-sort. One stable argsort, so the
    original relative order breaks ties and the permutation composes
    with the refill's contiguous free tail.
    """
    candidate = pk.instance_entry_candidates(
        origins, directions, lo_w, hi_w
    ).astype(jnp.uint32)
    candidate = jnp.minimum(candidate, jnp.uint32(1023))
    point = origins + directions
    lo = jnp.min(point, axis=0)
    span = jnp.maximum(jnp.max(point, axis=0) - lo, 1e-6)
    cell = ((point - lo) / span * 15.999).astype(jnp.uint32)  # 4 bits/axis
    morton = (
        _dilate4(cell[:, 0])
        | (_dilate4(cell[:, 1]) << 1)
        | (_dilate4(cell[:, 2]) << 2)
    )
    octant = (
        (directions[:, 0] > 0).astype(jnp.uint32)
        | ((directions[:, 1] > 0).astype(jnp.uint32) << 1)
        | ((directions[:, 2] > 0).astype(jnp.uint32) << 2)
    )
    fid_bits = jnp.minimum(fid.astype(jnp.uint32), jnp.uint32(31))
    dead = (~alive).astype(jnp.uint32) << 30
    key = (
        octant
        | (morton << 3)
        | (candidate << 15)
        | (fid_bits << 25)
        | dead
    )
    return jnp.argsort(key)


@functools.partial(
    jax.jit,
    static_argnames=(
        "scene_name", "width", "height", "samples", "max_bounces",
        "pool_width", "tile_shape", "use_tlas", "tlas_leaf", "tlas_block",
        "quant", "builder", "wide",
    ),
)
def _raypool_batch(
    scene_name: str,
    frames,  # [f_cap] float32 frame indices (tail-padded)
    n_frames,  # traced int32: frames actually served (<= f_cap)
    y0,  # traced int32 region origin (0 for whole frames)
    x0,
    *,
    width: int,
    height: int,
    samples: int,
    max_bounces: int,
    pool_width: int,
    tile_shape: tuple[int, int] | None = None,
    use_tlas: bool = True,
    tlas_leaf: int = 4,
    tlas_block: int = 256,
    quant: int = 0,
    builder: str = "sah",
    wide: int = 4,
):
    """The whole batch as ONE compiled program; returns
    (linear images [f_cap, H, W, 3], stats tuple).

    Everything here — primary-ray generation, the stacked multi-frame
    scene, the pool while-loop, per-frame averaging — lives in one XLA
    program. ``n_frames`` is TRACED, so one compile serves every batch
    size up to the window cap (the recompile bound the fixed pool width
    exists for). Every BVH env tier arrives RESOLVED as a static arg
    (``render_batch_raypool`` reads the env outside the trace — the
    env-tiers lint contract); ``quant`` >= 1 additionally packs the
    carried pool state (bf16 throughput words + one fid/bounce/dead meta
    column replacing three), shrinking the bytes the per-iteration
    permutation moves.
    """
    from tpu_render_cluster.render.camera import scene_camera
    from tpu_render_cluster.render.integrator import (
        frame_rays_and_seed,
        region_rays_and_seed,
    )
    from tpu_render_cluster.render.mesh import cached_mesh_bvh
    from tpu_render_cluster.render.scene import (
        build_mesh_instances,
        build_scene,
        mesh_kind_for_scene,
    )

    f_cap = frames.shape[0]
    if tile_shape is None:
        tile_height, tile_width = height, width
    else:
        tile_height, tile_width = tile_shape
    n = samples * tile_height * tile_width  # rays per frame (of this region)
    total = n_frames * n  # traced: primaries to serve
    pool = pool_width
    block = (
        pk.BVH_BLOCK_R
        if mesh_kind_for_scene(scene_name) is not None
        else pk.SPHERE_BOUNCE_BLOCK_R
    )

    # Primary rays + per-frame trace seeds, via the SAME helpers the
    # masked render_tile / region path use — the RNG/ray derivation
    # cannot drift. Under a region, each lane additionally maps to its
    # FULL-frame lane id (the RNG counter), so a tiled pool batch
    # reproduces the whole-frame streams on its pixels. The tile ORIGIN
    # (y0/x0) is traced — like the other two tiers, one compiled pool
    # program per tile SHAPE serves every position of the grid.
    glane_map = None
    if tile_shape is None:
        def frame_rays(frame):
            return frame_rays_and_seed(
                scene_camera(scene_name, frame), frame,
                width=width, height=height, samples=samples,
            )
    else:
        def frame_rays(frame):
            o, d, _lanes, seed = region_rays_and_seed(
                scene_camera(scene_name, frame), frame,
                width=width, height=height, samples=samples,
                y0=y0, x0=x0, tile_height=tile_height,
                tile_width=tile_width,
            )
            return o, d, seed

        # The local->full-frame lane map is frame-independent (every
        # frame serves the same region); in-graph arithmetic off the
        # traced origin, THE shared derivation (integrator.region_lane_map
        # — the same one region_rays_and_seed builds its lanes from).
        from tpu_render_cluster.render.integrator import region_lane_map

        glane_map = region_lane_map(
            y0=y0, x0=x0, tile_height=tile_height, tile_width=tile_width,
            width=width, height=height, samples=samples,
        )

    prim_o, prim_d, seeds = jax.vmap(frame_rays)(frames)
    prim_o = prim_o.reshape(f_cap * n, 3)
    prim_d = prim_d.reshape(f_cap * n, 3)

    # Stacked multi-frame scene: frame f's spheres carry fid f. The
    # lighting rows are frame-invariant by construction (build_scene's
    # _default_lighting) — take frame 0's.
    scenes = jax.vmap(lambda f: build_scene(scene_name, f))(frames)
    n_spheres = scenes.radii.shape[1]
    sphere_fid = jnp.repeat(jnp.arange(f_cap, dtype=jnp.int32), n_spheres)
    sphere_ops = pk.pool_sphere_operands(
        scenes.centers.reshape(-1, 3),
        scenes.radii.reshape(-1),
        scenes.albedo.reshape(-1, 3),
        scenes.emission.reshape(-1, 3),
        sphere_fid,
        scenes.sun_direction[0], scenes.sun_color[0],
        scenes.sky_horizon[0], scenes.sky_zenith[0],
        scenes.plane_albedo_a[0], scenes.plane_albedo_b[0],
    )

    mesh_kind = mesh_kind_for_scene(scene_name)
    tlas = False
    if mesh_kind is not None:
        # Shared topology, host-cached; the build knobs arrive resolved.
        bvh = cached_mesh_bvh(mesh_kind, builder, wide)
        inst = jax.vmap(lambda f: build_mesh_instances(scene_name, f))(
            frames
        )
        k = inst.translation.shape[1]
        mesh_ops = pk.PoolMeshOperands(
            spheres=sphere_ops,
            sun_direction=scenes.sun_direction[0],
            rotation=inst.rotation.reshape(-1, 3, 3),
            translation=inst.translation.reshape(-1, 3),
            scale=inst.scale.reshape(-1),
            inst_albedo=inst.albedo.reshape(-1, 3),
            ifid=jnp.repeat(jnp.arange(f_cap, dtype=jnp.int32), k),
            k_per_frame=k,
            v0=bvh.v0, e1=bvh.e1, e2=bvh.e2, normal=bvh.normal,
            bounds_min=bvh.bounds_min, bounds_max=bvh.bounds_max,
            skip=bvh.skip, first=bvh.first, count=bvh.count,
            octant=bvh.octant,
        )
        # ``use_tlas`` is a static resolved REQUEST; the actual decision
        # folds in the per-frame instance count, all concrete at trace
        # time (small fields degenerate to the flat sweep — the same
        # rule as pk.use_tlas_for, inlined so no env tier is read inside
        # this traced function).
        tlas = bool(use_tlas) and k > tlas_leaf
        if tlas:
            # The TLAS kernels packet at their own narrower block; it
            # always divides BVH_BLOCK_R, so the BVH_BLOCK_R-rounded
            # pool width stays valid and the launched-lane accounting
            # below matches the kernel's actual skip granularity.
            block = tlas_block
        if not tlas:
            # Sort-key broadphase over SLOT-UNION AABBs: slot k's world
            # AABB unioned across the window's frames, so the candidate
            # pass is [P, K] instead of [P, K*F] (measured ~126
            # ms/iteration of pure glue at F=8 on CPU). The candidate
            # only steers packing — fid sits ABOVE it in the key, so
            # within a frame group the union box is a slightly dilated
            # version of the frame's own box. The TLAS pool needs none
            # of this: its sort reads the key column the bounce kernel
            # emitted.
            inst_lo, inst_hi = pk.pool_instance_aabbs(mesh_ops)
            inst_lo = inst_lo.reshape(f_cap, k, 3).min(axis=0)
            inst_hi = inst_hi.reshape(f_cap, k, 3).max(axis=0)
    else:
        mesh_ops = None

    # Pool state. Unfilled lanes start dead with guaranteed-miss rays
    # (far origin, unit direction) so they can never degenerate a slab
    # test, and fid/lane 0 so their zero contributions scatter harmlessly.
    # quant >= 1 carries the PACKED tuple: throughput as bf16 words
    # ([pool, 2] f32) and ONE meta column (fid | bounce | dead) in place
    # of the separate alive/fid/bounce columns — the alive column is
    # dropped outright (it is the meta dead bit), so the per-iteration
    # permutation gathers 11 words per lane instead of 13 + a bool.
    packed_state = quant >= 1
    state = dict(
        o=jnp.full((pool, 3), 1e7, jnp.float32),
        d=jnp.broadcast_to(
            jnp.array([0.0, 1.0, 0.0], jnp.float32), (pool, 3)
        ),
        lane=jnp.zeros((pool,), jnp.int32),
        served=jnp.int32(0),
        it=jnp.int32(0),
        radiance=jnp.zeros((f_cap * n, 3), jnp.float32),
        occ_log=jnp.zeros((RAYPOOL_LOG_CAP,), jnp.float32),
        refill_log=jnp.zeros((RAYPOOL_LOG_CAP,), jnp.int32),
        refilled=jnp.int32(0),
        live_sum=jnp.float32(0.0),
        launched_sum=jnp.float32(0.0),
    )
    if packed_state:
        state["thr"] = pk.pack_throughput_bf16(
            jnp.ones((pool, 3), jnp.float32)
        )
        state["meta"] = pk.pack_pool_meta(
            jnp.zeros((pool,), jnp.int32),
            jnp.zeros((pool,), jnp.int32),
            jnp.zeros((pool,), bool),
        )
    else:
        state["thr"] = jnp.ones((pool, 3), jnp.float32)
        state["alive"] = jnp.zeros((pool,), bool)
        state["fid"] = jnp.zeros((pool,), jnp.int32)
        state["bounce"] = jnp.zeros((pool,), jnp.int32)
    if tlas:
        # Carried coherence-key column (the TLAS bounce kernel re-emits
        # it every iteration): every initial lane is dead, so one
        # constant dead-bit key is exact — the first sort is a stable
        # identity and the refill fills the pool head.
        state["key"] = jnp.full(
            (pool,), jnp.int32(1 << pk.KEY_DEAD_BIT), jnp.int32
        )
    # Backstop against a non-terminating loop under a lifecycle bug:
    # every iteration either serves new rays or ages live lanes toward
    # the bounce cap, so this bound is generous.
    iter_cap = (total // pool + 2) * (max_bounces + 1) + 4

    def pool_alive(s):
        if packed_state:
            return pk.unpack_pool_meta(s["meta"])[2]
        return s["alive"]

    def cond(s):
        return (s["it"] < iter_cap) & (
            (s["served"] < total) | jnp.any(pool_alive(s))
        )

    def body(s):
        if packed_state:
            s_fid, s_bounce, s_alive = pk.unpack_pool_meta(s["meta"])
        else:
            s_fid, s_bounce, s_alive = s["fid"], s["bounce"], s["alive"]
        # 1. One permutation: dead to the tail (+ frame/candidate/Morton
        # coherence for mesh scenes). The TLAS pool sorts by the key
        # column the previous iteration's bounce kernel emitted (dead
        # flag at pk.KEY_DEAD_BIT, fid above Morton — the same
        # live-grouping the flat key builds, minus the separate
        # broadphase pass).
        if mesh_ops is not None and tlas:
            perm = jnp.argsort(s["key"])
        elif mesh_ops is not None:
            perm = _pool_sort_order(
                s["o"], s["d"], s_alive, s_fid, inst_lo, inst_hi
            )
        else:
            perm, _ = compaction_order(s_alive)
        packed = jnp.concatenate([s["o"], s["d"], s["thr"]], axis=1)[perm]
        o, d = packed[:, 0:3], packed[:, 3:6]
        thr = packed[:, 6:]  # carried form: [P, 3] f32 or [P, 2] packed
        lane = s["lane"][perm]
        if packed_state:
            fid, bounce, alive = pk.unpack_pool_meta(s["meta"][perm])
        else:
            alive = s_alive[perm]
            fid = s_fid[perm]
            bounce = s_bounce[perm]
        live = jnp.sum(alive.astype(jnp.int32))

        # 2. Refill the freed tail with the next unserved primaries.
        take = jnp.minimum(pool - live, total - s["served"])
        slot = jnp.arange(pool, dtype=jnp.int32)
        src = jnp.clip(s["served"] + slot - live, 0, f_cap * n - 1)
        is_new = (slot >= live) & (slot < live + take)
        o = jnp.where(is_new[:, None], prim_o[src], o)
        d = jnp.where(is_new[:, None], prim_d[src], d)
        if packed_state:
            thr = jnp.where(
                is_new[:, None],
                pk.pack_throughput_bf16(jnp.ones((1, 3), jnp.float32)),
                thr,
            )
        else:
            thr = jnp.where(is_new[:, None], 1.0, thr)
        alive = alive | is_new
        new_fid = src // n
        fid = jnp.where(is_new, new_fid, fid)
        lane = jnp.where(is_new, src - new_fid * n, lane)
        bounce = jnp.where(is_new, 0, bounce)
        live2 = live + take

        # 3. One fused bounce over the live prefix (per-lane frame seed
        # + bounce depth key the RNG; all-dead tail blocks skip). Under a
        # region the RNG counter is the lane's FULL-frame id, not its
        # local scatter index. The kernel computes in f32 either way;
        # packed mode converts at the launch boundary.
        seed_row = seeds[jnp.clip(fid, 0, f_cap - 1)]
        rng = (
            lane if glane_map is None
            else glane_map[jnp.clip(lane, 0, n - 1)]
        )
        thr_f32 = pk.unpack_throughput_bf16(thr) if packed_state else thr
        if mesh_ops is not None:
            contrib, o, d, thr_f32, alive_k, key2 = pk.pool_mesh_bounce(
                mesh_ops, o, d, thr_f32, alive, rng, fid, seed_row,
                bounce, live2, total_bounces=max_bounces, use_tlas=tlas,
                tlas_leaf=tlas_leaf, tlas_block=tlas_block, quant=quant,
            )
        else:
            contrib, o, d, thr_f32, alive_k = pk.pool_sphere_bounce(
                sphere_ops, o, d, thr_f32, alive, rng, fid, seed_row,
                bounce, live2, total_bounces=max_bounces,
            )
            key2 = None
        thr = (
            pk.pack_throughput_bf16(thr_f32) if packed_state else thr_f32
        )

        # 4. Scatter-back into each lane's own frame buffer. Dead lanes
        # contribute exact zeros (alive-masked kernel math / skipped
        # blocks), so their stale indices are harmless. unique_indices
        # holds by construction: every (frame, lane) id is served into
        # exactly one pool slot and carried (live or stale) until that
        # slot is refilled with a NEVER-REUSED fresh id — so no two
        # slots ever hold the same id, and XLA may vectorize the scatter
        # instead of serializing it (a real cost on CPU).
        radiance = s["radiance"].at[fid * n + lane].add(
            contrib, unique_indices=True
        )

        # 5. Lifecycle + telemetry. Occupancy is measured against the
        # LAUNCHED width (live prefix rounded up to whole blocks — the
        # all-dead tail blocks beyond it skip the bounce and cost ~0),
        # the same basis as the wavefront driver's live/bucket, so the
        # three modes' wasted_lane_fraction records compare like for
        # like. live_sum tracks pool FULLNESS (live / pool width) for
        # the occupancy gauge.
        bounce = bounce + 1
        alive = alive_k & (bounce < max_bounces)
        log_at = jnp.minimum(s["it"], RAYPOOL_LOG_CAP - 1)
        launched = ((live2 + block - 1) // block) * block
        occupancy = live2.astype(jnp.float32) / jnp.maximum(launched, 1)
        next_state = dict(
            o=o, d=d, thr=thr, lane=lane,
            served=s["served"] + take,
            it=s["it"] + 1,
            radiance=radiance,
            occ_log=s["occ_log"].at[log_at].set(occupancy),
            refill_log=s["refill_log"].at[log_at].set(take),
            refilled=s["refilled"] + take,
            live_sum=s["live_sum"] + live2.astype(jnp.float32),
            launched_sum=s["launched_sum"] + launched.astype(jnp.float32),
        )
        if packed_state:
            next_state["meta"] = pk.pack_pool_meta(fid, bounce, alive)
        else:
            next_state["alive"] = alive
            next_state["fid"] = fid
            next_state["bounce"] = bounce
        if tlas:
            # The kernel keyed lanes by its OWN post-bounce alive; the
            # bounce-cap kill above happens out here, so stamp the dead
            # bit onto capped lanes or the next sort would keep funding
            # their packets instead of reclaiming them.
            next_state["key"] = jnp.where(
                alive, key2, key2 | jnp.int32(1 << pk.KEY_DEAD_BIT)
            )
        return next_state

    final = jax.lax.while_loop(cond, body, state)
    images = (
        final["radiance"]
        .reshape(f_cap, samples, tile_height * tile_width, 3)
        .mean(axis=1)
        .reshape(f_cap, tile_height, tile_width, 3)
    )
    stats = (
        final["it"], final["served"], final["refilled"],
        final["live_sum"], final["launched_sum"],
        final["occ_log"], final["refill_log"],
    )
    return images, stats


# -- host driver -------------------------------------------------------------


def _emit_batch_obs(
    *, scene_name, n_chunk_frames, pool, start_wall, duration,
    iterations, served, refilled, live_sum, launched_sum, occ_log,
    refill_log,
):
    """Feed registry + tracer from one batch's device-side telemetry.

    Per-iteration span timing is SYNTHETIC (batch wall time divided
    evenly — the device never reported per-iteration times, which is
    the whole point of the sync-free loop) and flagged as such;
    occupancy/refill span args are real device measurements.
    """
    from tpu_render_cluster.obs import get_tracer

    tracer = get_tracer()
    logged = min(iterations, RAYPOOL_LOG_CAP)
    histogram = pool_live_fraction_histogram()
    for i in range(logged):
        histogram.observe(float(occ_log[i]))
    if iterations:
        pool_occupancy_gauge().set(live_sum / (iterations * pool))
    pool_refill_counter().inc(refilled)
    pool_iteration_counter().inc(iterations)
    pool_launched_lanes_counter().inc(launched_sum)
    pool_live_lanes_counter().inc(live_sum)

    # Iteration spans first, batch span last: the trace-invariant checker
    # (obs/validate) requires non-decreasing span ends per track in append
    # order, and the iterations end inside the batch window.
    if logged:
        step = duration / logged
        for i in range(logged):
            tracer.complete(
                "raypool_iteration", cat="render",
                start_wall=start_wall + i * step, duration=step,
                track="raypool",
                args={
                    "iteration": i,
                    "occupancy": round(float(occ_log[i]), 4),
                    "refilled": int(refill_log[i]),
                    "synthetic_timing": True,
                },
            )
    tracer.complete(
        "raypool_batch", cat="render", start_wall=start_wall,
        duration=duration, track="raypool",
        args={
            "scene": scene_name,
            "frames": n_chunk_frames,
            "iterations": iterations,
            "rays_served": served,
            "rays_refilled": refilled,
            "pool_width": pool,
            "occupancy_mean": (
                round(live_sum / (iterations * pool), 4) if iterations else 0.0
            ),
            "log_truncated": iterations > RAYPOOL_LOG_CAP,
        },
    )


def render_batch_raypool(
    scene_name: str,
    frame_indices,
    *,
    width: int = 512,
    height: int = 512,
    samples: int = 8,
    max_bounces: int = 4,
    pool_width: int | None = None,
    frame_cap: int | None = None,
    region: tuple[int, int, int, int] | None = None,
    use_tlas: bool | None = None,
    quant: int | None = None,
    builder: str | None = None,
    wide: int | None = None,
):
    """Render a batch of frames through the device-resident ray pool.

    Returns a list of linear [H, W, 3] numpy images, one per entry of
    ``frame_indices`` in order. Batches larger than the frame-window
    cap chunk into windows (one host sync per window); every window of
    any size reuses the one compiled program for this pool config.

    ``region`` = (y0, x0, tile_height, tile_width) restricts every frame
    of the batch to ONE tile region (the cluster-tiling work unit): the
    pool serves the region's rays with their full-frame RNG lane ids, so
    the returned [th, tw, 3] images equal the whole-frame pool render's
    pixels on the region. The batch dimension stays FRAMES — a tiled
    multi-frame job batches same-tile units across frames.
    """
    import numpy as np

    from tpu_render_cluster.render.scene import mesh_kind_for_scene

    frames = [int(f) for f in frame_indices]
    if not frames:
        return []
    f_cap = frame_cap if frame_cap is not None else raypool_frame_cap()
    f_cap = max(1, min(f_cap, RAYPOOL_MAX_FRAMES))
    if region is not None:
        region = tuple(int(v) for v in region)
        n = samples * region[2] * region[3]
    else:
        n = samples * height * width
    block = (
        pk.BVH_BLOCK_R
        if mesh_kind_for_scene(scene_name) is not None
        else pk.SPHERE_BOUNCE_BLOCK_R
    )
    pool = pool_width if pool_width is not None else raypool_width(n, block)
    pool = max(block, -(-pool // block) * block)
    # Resolve every BVH env tier HERE, outside the traced batch program
    # (the env-tiers lint contract), and thread the concrete values in as
    # static args — they are part of the pool program's identity, its
    # compile-count key, and its roofline row. The tlas tag mirrors the
    # RESOLVED request; kernel selection still auto-degrades tiny
    # instance fields inside the batch program.
    from tpu_render_cluster.obs.profiling import bvh_dims
    from tpu_render_cluster.render.integrator import resolve_bvh_config

    tlas_resolved, quant, builder, wide = resolve_bvh_config(
        use_tlas, quant, builder, wide
    )
    tlas_leaf = pk.tlas_leaf_size()
    tlas_block = pk.tlas_block_r()
    format_dims = bvh_dims(
        tlas=tlas_resolved, quant=quant, builder=builder, wide=wide
    )

    images: list = []
    for start in range(0, len(frames), f_cap):
        chunk = frames[start:start + f_cap]
        padded = chunk + [chunk[-1]] * (f_cap - len(chunk))
        note_compile(
            "raypool", scene_name, width, height, samples, max_bounces,
            pool, f_cap, None if region is None else (region[2], region[3]),
            int(tlas_resolved), quant, builder, wide,
        )
        start_wall = time.time()
        start_mono = time.perf_counter()
        linear, stats = _raypool_batch(
            scene_name,
            jnp.asarray(padded, jnp.float32),
            jnp.int32(len(chunk)),
            jnp.int32(0 if region is None else region[0]),
            jnp.int32(0 if region is None else region[1]),
            width=width, height=height, samples=samples,
            max_bounces=max_bounces, pool_width=pool,
            tile_shape=None if region is None else (region[2], region[3]),
            use_tlas=tlas_resolved, tlas_leaf=tlas_leaf,
            tlas_block=tlas_block, quant=quant, builder=builder,
            wide=wide,
        )
        # THE host sync of the batch: everything before this line is one
        # dispatched XLA program.
        linear = np.asarray(linear)
        (iterations, served, refilled, live_sum, launched_sum, occ_log,
         refill_log) = (
            int(stats[0]), int(stats[1]), int(stats[2]),
            float(stats[3]), float(stats[4]),
            np.asarray(stats[5]), np.asarray(stats[6]),
        )
        duration = time.perf_counter() - start_mono
        # Roofline profiling: capture the pool program's cost analysis
        # once per pool config (the same identity note_compile tracks;
        # one extra lowering, no second backend compile) — AFTER the
        # duration stamp so the capture never inflates the first batch's
        # measured time. The batch is ONE device dispatch fenced by the
        # np.asarray above, so `duration` is the program's true wall time
        # (per BATCH — the view divides by executions).
        from tpu_render_cluster.obs.profiling import get_profiler, kernel_key

        profiler = get_profiler()
        pool_key = kernel_key(
            "raypool", scene_name,
            w=width, h=height, s=samples, b=max_bounces,
            pool=pool, frames=f_cap,
            tile="-" if region is None else f"{region[2]}x{region[3]}",
            **format_dims,
        )
        if not profiler.captured(pool_key):
            profiler.capture(
                pool_key, _raypool_batch, scene_name,
                jnp.asarray(padded, jnp.float32), jnp.int32(len(chunk)),
                jnp.int32(0 if region is None else region[0]),
                jnp.int32(0 if region is None else region[1]),
                width=width, height=height, samples=samples,
                max_bounces=max_bounces, pool_width=pool,
                tile_shape=None if region is None else (region[2], region[3]),
                use_tlas=tlas_resolved, tlas_leaf=tlas_leaf,
                tlas_block=tlas_block, quant=quant, builder=builder,
                wide=wide,
            )
        profiler.record_execute(pool_key, duration)
        _emit_batch_obs(
            scene_name=scene_name, n_chunk_frames=len(chunk), pool=pool,
            start_wall=start_wall, duration=duration,
            iterations=iterations, served=served, refilled=refilled,
            live_sum=live_sum, launched_sum=launched_sum,
            occ_log=occ_log, refill_log=refill_log,
        )
        images.extend(linear[:len(chunk)])
    return images


def render_frame_raypool(scene_name: str, frame_index, **kwargs):
    """Single-frame convenience wrapper; [H, W, 3] linear."""
    return render_batch_raypool(scene_name, [frame_index], **kwargs)[0]
