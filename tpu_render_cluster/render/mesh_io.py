"""Wavefront OBJ ingest for the TPU mesh path.

The reference's workers render arbitrary user content by shelling out to
Blender (reference: worker/src/rendering/runner/mod.rs:165-176 — whatever
the .blend contains). The TPU tracer's counterpart for user geometry is
this loader: triangles from an OBJ file feed the same host-built threaded
BVH (`mesh.build_bvh`) and traverse with the same Pallas kernels as the
procedural meshes — topology is loaded once on the host and becomes
static device arrays, so arbitrary meshes compose into jit/vmap exactly
like the built-ins.

Supported OBJ subset: `v` positions, `f` faces with any of the index
forms (`v`, `v/vt`, `v/vt/vn`, `v//vn`), negative (relative) indices,
absolute indices forward-referencing later `v` lines, polygon faces
(triangulated as a fan), comments, and all other statements ignored (normals are recomputed per-face by `build_bvh`; materials are a
per-instance albedo in this renderer).
"""

from __future__ import annotations

import functools
from pathlib import Path

import numpy as np


def load_obj(path: str | Path) -> tuple[np.ndarray, np.ndarray]:
    """Parse an OBJ file into (vertices [V,3] f32, faces [F,3] i32)."""
    vertices: list[tuple[float, float, float]] = []
    # Faces are collected as raw tokens and resolved only after the whole
    # file is read: absolute indices may legally forward-reference `v`
    # lines that appear later. Negative (relative) indices are resolved
    # against the vertex count AT the `f` statement, per the OBJ spec, so
    # that count is recorded alongside the tokens.
    pending_faces: list[tuple[int, int, list[str]]] = []

    with open(path, encoding="utf-8", errors="replace") as handle:
        for line_number, line in enumerate(handle, 1):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            if parts[0] == "v":
                if len(parts) < 4:
                    # Must be fatal: silently dropping a malformed vertex
                    # would shift every subsequent face index.
                    raise ValueError(
                        f"{path}:{line_number}: vertex needs 3 coordinates"
                    )
                vertices.append(
                    (float(parts[1]), float(parts[2]), float(parts[3]))
                )
            elif parts[0] == "f":
                if len(parts) < 4:
                    raise ValueError(
                        f"{path}:{line_number}: face needs >=3 vertices"
                    )
                pending_faces.append((line_number, len(vertices), parts[1:]))
            # vn/vt/o/g/s/usemtl/mtllib: ignored (see module docstring).

    def resolve(token: str, line_number: int, vertex_count_at_face: int) -> int:
        # "v", "v/vt", "v/vt/vn", "v//vn" -> vertex index (1-based;
        # negative = relative to the vertices seen up to the f statement).
        raw = token.split("/", 1)[0]
        index = int(raw)
        if index < 0:
            index += vertex_count_at_face
            if index < 0:
                raise ValueError(
                    f"{path}:{line_number}: OBJ relative index out of range: {token}"
                )
            return index
        if not 1 <= index <= len(vertices):
            raise ValueError(
                f"{path}:{line_number}: OBJ vertex index out of range: {token}"
            )
        return index - 1

    faces: list[tuple[int, int, int]] = []
    for line_number, vertex_count_at_face, tokens in pending_faces:
        ring = [resolve(token, line_number, vertex_count_at_face) for token in tokens]
        for i in range(1, len(ring) - 1):  # fan triangulation
            faces.append((ring[0], ring[i], ring[i + 1]))

    if not vertices or not faces:
        raise ValueError(f"{path}: no triangles found")
    return (
        np.asarray(vertices, np.float32),
        np.asarray(faces, np.int32),
    )


def normalize_to_stage(
    vertices: np.ndarray, *, target_extent: float = 2.0
) -> np.ndarray:
    """Center the mesh at the origin and scale its largest extent to
    ``target_extent`` — user OBJs arrive in arbitrary units, the stage
    scene (cli --obj) expects roughly unit-scale geometry resting above
    the ground plane."""
    lo = vertices.min(axis=0)
    hi = vertices.max(axis=0)
    center = 0.5 * (lo + hi)
    extent = float((hi - lo).max())
    scale = target_extent / max(extent, 1e-9)
    return ((vertices - center) * scale).astype(np.float32)


@functools.lru_cache(maxsize=8)
def _cached_obj_bvh_impl(resolved: str, mtime_ns: int):
    from tpu_render_cluster.render.mesh import build_bvh

    vertices, faces = load_obj(resolved)
    return build_bvh(normalize_to_stage(vertices), faces)


def cached_obj_bvh(path: str | Path):
    """BVH for an OBJ file, cached on (path, mtime) like the procedural
    meshes are cached on kind."""
    resolved = Path(path).resolve()
    return _cached_obj_bvh_impl(str(resolved), resolved.stat().st_mtime_ns)
