"""Wavefront path tracing: active-ray compaction + bucketed relaunch.

The masked bounce loop (integrator.trace_paths) marches EVERY lane
through every bounce; after bounce 1 most lanes carry dead paths that
still occupy kernel lanes (and, before the live-count prefetch, still
drove BVH packet walks). Wavefront execution fixes the occupancy: after
each bounce the live rays are stream-compacted to the front, the live
count is read back, rounded UP to a small ladder of power-of-two bucket
sizes (the same bucketed-jit idiom as ops/assignment.py — XLA compiles
once per bucket, not per live count), and the next bounce is relaunched
over the compacted bucket only. Radiance scatters back through the
carried ORIGINAL lane ids, which also key the kernels' counter-based
RNG — so a ray's stream is identical whether it rides the masked loop,
the megakernel, or any compacted position here (the RNG-stability
contract that makes masked-vs-wavefront images comparable).

Two cooperating mechanisms, one per execution mode:

- IN-JIT compaction (integrator.trace_paths): the per-bounce Morton
  re-sort already parks dead lanes at the tail; the bounce kernels now
  take a live-count scalar and skip all-dead tail blocks. Shapes stay
  static, so this composes with jit/vmap/shard_map (tile/spp sharding)
  — but the launch width never shrinks.
- HOST-DRIVEN bucketed relaunch (this module): one device sync per
  bounce buys dynamically shrinking launch widths. Runs outside jit, so
  it is a per-frame driver (the worker backend's wavefront mode), not a
  drop-in for the fused renderer.

Instrumented via obs/: ``render_lane_occupancy`` gauge (live / launched
width of the last relaunch), ``render_alive_fraction`` per-bounce
histogram (live / original wavefront — the survival curve bench.py
folds into ``wasted_lane_fraction``), ``render_compiles_total`` counter
(new bucket shapes — the recompile bound the bucketing exists for), and
per-bounce spans on the process tracer (Perfetto-visible).
"""

from __future__ import annotations

import functools
import os
import time

import jax
import jax.numpy as jnp

from tpu_render_cluster.render import pallas_kernels as pk

# Linear bucket bounds for the alive-fraction histogram: fractions live
# in [0, 1], where the default log ladder (1e-4..1e3) has almost no
# resolution. One definition site (like obs.render_fps_gauge) so every
# process files observations into merge-compatible buckets.
ALIVE_FRACTION_BUCKETS = tuple((i + 1) / 16 for i in range(16))


def lane_occupancy_gauge(registry=None):
    """live / launched-width of the most recent wavefront relaunch."""
    from tpu_render_cluster.obs import get_registry

    registry = registry if registry is not None else get_registry()
    return registry.gauge(
        "render_lane_occupancy",
        "Live-lane fraction of the last wavefront bounce launch "
        "(live rays / bucketed launch width)",
    )


def alive_fraction_histogram(registry=None):
    """Per-bounce survival: live rays / original wavefront size."""
    from tpu_render_cluster.obs import get_registry

    registry = registry if registry is not None else get_registry()
    return registry.histogram(
        "render_alive_fraction",
        "Per-bounce live fraction of the original wavefront "
        "(1 - this, averaged, is bench.py's wasted_lane_fraction)",
        labels=("bounce",),
        buckets=ALIVE_FRACTION_BUCKETS,
    )


def launch_occupancy_histogram(registry=None):
    """Per-relaunch live fraction of the LAUNCHED bucket (live / bucket).

    The survival histogram above measures the scene (live / original
    wavefront — what a full-width masked loop wastes); this one measures
    the DRIVER (how much of what it actually launched was live), which
    is what the bucketed reclaim improves and what the ray pool's
    render_pool_live_fraction is compared against in bench.py's
    three-way record.
    """
    from tpu_render_cluster.obs import get_registry

    registry = registry if registry is not None else get_registry()
    return registry.histogram(
        "render_launch_occupancy",
        "Per-bounce live fraction of the launched wavefront bucket "
        "(1 - this, averaged, is the wavefront driver's own "
        "wasted_lane_fraction)",
        buckets=ALIVE_FRACTION_BUCKETS,
    )


def compile_counter(registry=None):
    from tpu_render_cluster.obs import get_registry

    registry = registry if registry is not None else get_registry()
    return registry.counter(
        "render_compiles_total",
        "Wavefront programs compiled (first sighting of a (kind, bucket) "
        "shape this process) — grows with the bucket ladder, not frames",
    )


# First-sighting tracker behind render_compiles_total. Python-level on
# purpose: it counts the shapes the drivers have launched (the quantity
# the bucket ladder / fixed pool width bounds), independent of jax cache
# internals. Keyed per DRIVER KIND (wavefront vs raypool) so the two
# drivers' key namespaces can't collide, and resettable so tests can
# assert on compile-count deltas without inheriting another test's
# sightings (tests/conftest.py resets it around every test).
_seen_shapes: dict[str, set[tuple]] = {}


def note_compile(driver: str, *key) -> None:
    """Count a first-sighting of ``key`` for ``driver`` into
    render_compiles_total (idempotent per (driver, key))."""
    seen = _seen_shapes.setdefault(driver, set())
    if key not in seen:
        seen.add(key)
        compile_counter().inc()


def reset_compile_tracking(driver: str | None = None) -> None:
    """Forget first-sightings (one driver kind, or all).

    Test isolation only: the obs counter itself keeps its process-wide
    value (counters are monotonic); resetting merely makes the next
    sighting of a shape count again, so per-test DELTA assertions are
    independent of which shapes earlier tests visited.
    """
    if driver is None:
        _seen_shapes.clear()
    else:
        _seen_shapes.pop(driver, None)


def _count_compile(*key) -> None:
    note_compile("wavefront", *key)


def bucket_for(live: int, cap: int, block: int) -> int:
    """Smallest power-of-two multiple of ``block`` >= ``live``, <= ``cap``.

    The relaunch ladder: block, 2*block, 4*block, ... — at most
    log2(cap / block) + 1 distinct jit shapes per (scene, config), the
    same compile-once-per-bucket idiom as ops/assignment._next_bucket.
    """
    size = block
    while size < live:
        size *= 2
    return min(size, cap)


@jax.jit
def compaction_order(alive):
    """Stable partition permutation via prefix sums: alive lanes first.

    Returns (perm, live) with ``x[perm]`` compacted — live lanes in
    their original relative order, then the dead tail. A cumsum scatter,
    not an argsort: O(n) work and no comparison sort on the hot path.
    """
    alive_i32 = alive.astype(jnp.int32)
    live = jnp.sum(alive_i32)
    front = jnp.cumsum(alive_i32) - 1
    back = live + jnp.cumsum(1 - alive_i32) - 1
    dest = jnp.where(alive, front, back)
    n = alive.shape[0]
    perm = jnp.zeros((n,), jnp.int32).at[dest].set(
        jnp.arange(n, dtype=jnp.int32)
    )
    return perm, live


@jax.jit
def _compact_sphere(origins, directions, throughput, alive, lane, rng):
    """Compact sphere-scene state (no coherence sort needed — the sphere
    pass has no packet culling, so only the dead/alive partition
    matters). One packed gather so the random-access cost is paid once
    per row, not per field. ``rng`` is the RNG-counter row riding next
    to the scatter index ``lane`` (identical arrays unless the caller
    renders a region with full-frame lane ids — XLA CSEs the duplicate
    gather away in the identical case)."""
    perm, live = compaction_order(alive)
    packed = jnp.concatenate([origins, directions, throughput], axis=1)[perm]
    return (
        packed[:, 0:3],
        packed[:, 3:6],
        packed[:, 6:],  # width-generic: f32 [R, 3] or bf16-packed [R, 2]
        alive[perm],
        lane[perm],
        rng[perm],
        live,
    )


@jax.jit
def _compact_mesh(origins, directions, throughput, alive, lane, rng, mesh):
    """Compact mesh-scene state with the integrator's coherence sort.

    _ray_sort_order's dead flag (bit 31) already parks dead lanes at the
    tail, so the Morton/candidate re-sort IS the compaction permutation
    — one gather buys both packet coherence and the partition.
    """
    from tpu_render_cluster.render.integrator import _ray_sort_order

    order = _ray_sort_order(origins, directions, alive, mesh=mesh)
    packed = jnp.concatenate([origins, directions, throughput], axis=1)[order]
    return (
        packed[:, 0:3],
        packed[:, 3:6],
        packed[:, 6:],
        alive[order],
        lane[order],
        rng[order],
        jnp.sum(alive.astype(jnp.int32)),
    )


@jax.jit
def _compact_mesh_keyed(origins, directions, throughput, alive, lane, rng,
                        keys):
    """Compact mesh-scene state by the PRECOMPUTED coherence key column.

    The TLAS bounce kernels emit the next bounce's sort key from their
    epilogue (pallas_kernels.coherence_key_u32 — dead flag at
    KEY_DEAD_BIT, 29), so
    the re-sort here is one argsort over an int32 column instead of the
    separate XLA broadphase + quantization pass ``_compact_mesh`` pays
    over the full ray state. Same contract: dead lanes to the tail, one
    packed gather for coherence AND partition.
    """
    order = jnp.argsort(keys)
    packed = jnp.concatenate([origins, directions, throughput], axis=1)[order]
    return (
        packed[:, 0:3],
        packed[:, 3:6],
        packed[:, 6:],
        alive[order],
        lane[order],
        rng[order],
        jnp.sum(alive.astype(jnp.int32)),
    )


@jax.jit
def _initial_mesh_keys(origins, directions, alive, mesh):
    """Bounce-0 coherence keys for the TLAS wavefront: the XLA twin of
    the kernel epilogue, via THE shared derivation
    (pallas_kernels.initial_mesh_sort_keys — the deep per-bounce path
    keys through the same site). Frame-dependent, never ray-dependent,
    so every launch of a frame keys identically; bounces > 0 read the
    kernel-emitted column."""
    return pk.initial_mesh_sort_keys(mesh, origins, directions, alive)


@functools.partial(jax.jit, static_argnames=("total_bounces", "quant"))
def _sphere_step(
    scene, origins, directions, throughput, alive, lane, rng, live, seed,
    bounce, radiance_total, *, total_bounces: int, quant: int = 0,
):
    # quant >= 1: the carried throughput column is bf16-packed ([R, 2]
    # f32 words) — the packed-carried-state half of the TRC_BVH_QUANT
    # tier. The kernel still computes in f32; the pack/unpack round-trip
    # per bounce is the divergence tests/test_bvhq.py budgets.
    thr = pk.unpack_throughput_bf16(throughput) if quant else throughput
    contribution, o2, d2, thr2, alive2 = pk.sphere_bounce_pallas(
        scene, origins, directions, thr, alive, seed, bounce,
        total_bounces=total_bounces, lane=rng, live_count=live,
    )
    if quant:
        thr2 = pk.pack_throughput_bf16(thr2)
    return o2, d2, thr2, alive2, radiance_total.at[lane].add(contribution)


@functools.partial(
    jax.jit,
    static_argnames=("total_bounces", "use_tlas", "quant", "tlas_block"),
)
def _mesh_step(
    scene, mesh, origins, directions, throughput, alive, lane, rng, live, seed,
    bounce, radiance_total, *, total_bounces: int, use_tlas: bool = False,
    quant: int = 0, tlas_block: int = 256,
):
    thr = pk.unpack_throughput_bf16(throughput) if quant else throughput
    contribution, o2, d2, thr2, alive2, keys2 = pk.mesh_bounce_pallas(
        scene, mesh, origins, directions, thr, alive, seed, bounce,
        total_bounces=total_bounces, lane=rng, live_count=live,
        use_tlas=use_tlas, quant=quant, tlas_block=tlas_block,
    )
    if quant:
        thr2 = pk.pack_throughput_bf16(thr2)
    return (
        o2, d2, thr2, alive2, radiance_total.at[lane].add(contribution),
        keys2,
    )


def trace_paths_wavefront(
    scene, origins, directions, seed, *, max_bounces: int = 4, mesh=None,
    rng_lanes=None, use_tlas=None, quant=None,
):
    """Trace one sample per ray, wavefront-style; returns radiance [R, 3].

    The host-driven loop: compact -> read live count (ONE device sync
    per bounce — the price of dynamic launch widths) -> round up to a
    bucket -> relaunch the fused bounce kernel over the bucket only ->
    scatter the contribution back through the carried lane ids. An
    all-dead wavefront ends the loop early (remaining bounces cannot
    contribute).

    Physics and per-original-lane RNG streams are identical to the
    masked Pallas paths (integrator.trace_paths with TRC_PALLAS on), so
    images agree up to FP tie-breaking — tests/test_wavefront.py pins
    the equivalence. ``rng_lanes`` (optional [R] int32) overrides the
    RNG counters with FULL-frame lane ids: the cluster-tile region path
    (render_region_wavefront) uses it so a tiled wavefront frame
    reproduces the whole-frame wavefront image on its pixels.
    ``use_tlas`` (None = env tier) selects the two-level mesh kernel
    variant; with it, each bounce's compaction reads the key column the
    previous bounce kernel emitted instead of re-deriving keys.
    """
    from tpu_render_cluster.obs import get_tracer

    n0 = origins.shape[0]
    kind = "mesh" if mesh is not None else "sphere"
    tlas = (
        pk.use_tlas_for(mesh.instances.translation.shape[0], use_tlas)
        if mesh is not None else False
    )
    # Node-format tier (None = TRC_BVH_QUANT): quantized node tables in
    # the bounce kernels AND the bf16-packed carried throughput the
    # compaction gathers move — both halves flip together so the A/B
    # bench's variants stay whole.
    quant = pk.bvh_quant_mode() if quant is None else max(0, min(int(quant), 2))
    # The bucket quantum is the kernel's ray block: the TLAS kernels
    # packet at the narrower tlas_block_r, which also buys the ladder
    # finer reclaim granularity.
    tlas_block = pk.tlas_block_r()
    if mesh is None:
        block = pk.SPHERE_BOUNCE_BLOCK_R
    elif tlas:
        block = tlas_block
    else:
        block = pk.BVH_BLOCK_R
    tracer = get_tracer()
    occupancy = lane_occupancy_gauge()
    survival = alive_fraction_histogram()
    launched = launch_occupancy_histogram()

    radiance_total = jnp.zeros((n0, 3), jnp.float32)
    throughput = jnp.ones((n0, 3), jnp.float32)
    if quant:
        throughput = pk.pack_throughput_bf16(throughput)
    alive = jnp.ones((n0,), bool)
    lane = jnp.arange(n0, dtype=jnp.int32)
    rng = lane if rng_lanes is None else jnp.asarray(rng_lanes, jnp.int32)
    seed = jnp.asarray(seed, jnp.int32)
    keys = _initial_mesh_keys(origins, directions, alive, mesh) if tlas else None

    for bounce in range(max_bounces):
        start_wall = time.time()
        start_mono = time.perf_counter()
        width = origins.shape[0]
        _count_compile(kind, "compact", width)
        if tlas:
            origins, directions, throughput, alive, lane, rng, live_dev = (
                _compact_mesh_keyed(
                    origins, directions, throughput, alive, lane, rng, keys
                )
            )
        elif mesh is not None:
            origins, directions, throughput, alive, lane, rng, live_dev = (
                _compact_mesh(
                    origins, directions, throughput, alive, lane, rng, mesh
                )
            )
        else:
            origins, directions, throughput, alive, lane, rng, live_dev = (
                _compact_sphere(
                    origins, directions, throughput, alive, lane, rng
                )
            )
        live = int(live_dev)
        survival.observe(live / n0, bounce=bounce)
        if live == 0:
            occupancy.set(0.0)
            tracer.complete(
                "wavefront_bounce", cat="render", start_wall=start_wall,
                duration=time.perf_counter() - start_mono,
                track="wavefront",
                args={"bounce": bounce, "live": 0, "bucket": 0,
                      "alive_fraction": 0.0},
            )
            break
        bucket = bucket_for(live, cap=width, block=block)
        if bucket < width:
            origins = origins[:bucket]
            directions = directions[:bucket]
            throughput = throughput[:bucket]
            alive = alive[:bucket]
            lane = lane[:bucket]
            rng = rng[:bucket]
        occupancy.set(live / bucket)
        launched.observe(live / bucket)
        _count_compile(kind, "bounce", bucket, max_bounces, tlas, quant)
        # Roofline profiling: the bucket program's identity is (kind,
        # bucket, bounces, node format) — the same identity the
        # bucketed-jit cache compiles per. The capture args are stashed
        # BEFORE the step reassigns them, but the lowering itself runs
        # after the bounce's duration stamp so it never inflates a
        # measured bounce. The builder/wide dims tag which BLAS build the
        # mesh passed in carries (callers building a non-default tree
        # pass env overrides through scene_mesh_set, so the env tiers
        # describe it).
        from tpu_render_cluster.obs.profiling import (
            bvh_dims,
            get_profiler,
            kernel_key,
        )
        from tpu_render_cluster.render.mesh import bvh_builder, bvh_wide

        profiler = get_profiler()
        step_key = kernel_key(
            f"wavefront_{kind}_bounce", None, bucket=bucket, b=max_bounces,
            **bvh_dims(tlas=tlas, quant=quant, builder=bvh_builder(),
                       wide=bvh_wide()),
        )
        capture_args = None
        if not profiler.captured(step_key):
            capture_args = (
                (scene, mesh, origins, directions, throughput, alive, lane,
                 rng, live_dev, seed, bounce, radiance_total)
                if mesh is not None
                else (scene, origins, directions, throughput, alive, lane,
                      rng, live_dev, seed, bounce, radiance_total)
            )
        if mesh is not None:
            (origins, directions, throughput, alive, radiance_total,
             keys) = _mesh_step(
                scene, mesh, origins, directions, throughput, alive,
                lane, rng, live_dev, seed, bounce, radiance_total,
                total_bounces=max_bounces, use_tlas=tlas, quant=quant,
                tlas_block=tlas_block,
            )
        else:
            origins, directions, throughput, alive, radiance_total = (
                _sphere_step(
                    scene, origins, directions, throughput, alive, lane,
                    rng, live_dev, seed, bounce, radiance_total,
                    total_bounces=max_bounces, quant=quant,
                )
            )
        bounce_seconds = time.perf_counter() - start_mono
        # Measured-time pairing for the roofline view: the host-driven
        # loop syncs once per bounce, so the bounce wall time (compact +
        # live-count sync + step dispatch) is the tier's honest per-launch
        # cost — there is no tighter device fence to pair with.
        profiler.record_execute(step_key, bounce_seconds)
        if capture_args is not None:
            if mesh is not None:
                profiler.capture(
                    step_key, _mesh_step, *capture_args,
                    total_bounces=max_bounces, use_tlas=tlas, quant=quant,
                    tlas_block=tlas_block,
                )
            else:
                profiler.capture(
                    step_key, _sphere_step, *capture_args,
                    total_bounces=max_bounces, quant=quant,
                )
        tracer.complete(
            "wavefront_bounce", cat="render", start_wall=start_wall,
            duration=bounce_seconds,
            track="wavefront",
            args={"bounce": bounce, "live": live, "bucket": bucket,
                  "alive_fraction": round(live / n0, 4)},
        )
    return radiance_total


@functools.partial(
    jax.jit, static_argnames=("width", "height", "samples")
)
def _frame_rays(camera, frame, *, width: int, height: int, samples: int):
    """Primary rays for a full frame, samples flattened onto the ray axis.

    Built from render_tile's OWN helper (integrator.frame_rays_and_seed,
    also the ray-pool driver's source), so a wavefront frame and a
    masked frame provably trace the same physical rays with the same
    per-lane RNG streams — the derivation cannot drift.
    """
    from tpu_render_cluster.render.integrator import frame_rays_and_seed

    return frame_rays_and_seed(
        camera, frame, width=width, height=height, samples=samples
    )


@functools.partial(jax.jit, static_argnames=("samples", "height", "width"))
def _finish_frame(radiance, *, samples: int, height: int, width: int):
    n = height * width
    return radiance.reshape(samples, n, 3).mean(axis=0).reshape(
        height, width, 3
    )


def render_frame_wavefront(
    scene_name: str,
    frame_index,
    *,
    width: int = 512,
    height: int = 512,
    samples: int = 8,
    max_bounces: int = 4,
    use_tlas=None,
    quant=None,
):
    """Render one frame through the wavefront driver; [H, W, 3] linear.

    The wavefront counterpart of integrator.render_frame /
    fused_frame_renderer. Not a single fused dispatch — the driver's
    per-bounce host sync is the mechanism — so scene/camera build runs
    eagerly; that cost is noise on the deep-walk scenes this mode is
    for.
    """
    from tpu_render_cluster.render.camera import scene_camera
    from tpu_render_cluster.render.mesh import scene_mesh_set
    from tpu_render_cluster.render.scene import build_scene

    scene = build_scene(scene_name, frame_index)
    camera = scene_camera(scene_name, frame_index)
    mesh = scene_mesh_set(scene_name, frame_index)
    origins, directions, seed = _frame_rays(
        camera, jnp.asarray(frame_index, jnp.float32),
        width=width, height=height, samples=samples,
    )
    radiance = trace_paths_wavefront(
        scene, origins, directions, seed, max_bounces=max_bounces, mesh=mesh,
        use_tlas=use_tlas, quant=quant,
    )
    return _finish_frame(
        radiance, samples=samples, height=height, width=width
    )


@functools.partial(
    jax.jit,
    static_argnames=("width", "height", "samples", "tile_height", "tile_width"),
)
def _region_rays(
    camera, frame, y0, x0, *, width: int, height: int, samples: int,
    tile_height: int, tile_width: int,
):
    from tpu_render_cluster.render.integrator import region_rays_and_seed

    return region_rays_and_seed(
        camera, frame, width=width, height=height, samples=samples,
        y0=y0, x0=x0, tile_height=tile_height, tile_width=tile_width,
    )


def render_region_wavefront(
    scene_name: str,
    frame_index,
    *,
    y0: int,
    x0: int,
    tile_height: int,
    tile_width: int,
    width: int = 512,
    height: int = 512,
    samples: int = 8,
    max_bounces: int = 4,
    use_tlas=None,
    quant=None,
):
    """Render one region of a frame through the wavefront driver.

    The cluster-tile counterpart of ``render_frame_wavefront``: region
    rays + full-frame RNG lane ids (integrator.region_rays_and_seed), so
    a stitched grid of regions reproduces the whole-frame wavefront
    image — the worker's wavefront tier serves tile work units through
    here. Returns [tile_height, tile_width, 3] linear radiance.
    """
    from tpu_render_cluster.render.camera import scene_camera
    from tpu_render_cluster.render.mesh import scene_mesh_set
    from tpu_render_cluster.render.scene import build_scene

    scene = build_scene(scene_name, frame_index)
    camera = scene_camera(scene_name, frame_index)
    mesh = scene_mesh_set(scene_name, frame_index)
    origins, directions, lanes, seed = _region_rays(
        camera, jnp.asarray(frame_index, jnp.float32),
        jnp.asarray(y0, jnp.int32), jnp.asarray(x0, jnp.int32),
        width=width, height=height, samples=samples,
        tile_height=tile_height, tile_width=tile_width,
    )
    radiance = trace_paths_wavefront(
        scene, origins, directions, seed, max_bounces=max_bounces,
        mesh=mesh, rng_lanes=lanes, use_tlas=use_tlas, quant=quant,
    )
    return _finish_frame(
        radiance, samples=samples, height=tile_height, width=tile_width
    )


def wavefront_active(
    scene_name: str, *, backend_flag: str | None = None, frame=1
) -> bool:
    """Whether the wavefront driver should render this scene.

    ``backend_flag`` (the worker's ``--wavefront`` / constructor knob)
    overrides the ``TRC_WAVEFRONT`` env tier; ``auto`` defers to the
    per-scene heuristic (deep-walk mesh scenes — exactly the scenes the
    per-bounce dispatch already routes away from the megakernel).
    """
    if not pk.pallas_enabled():
        return False
    mode = backend_flag if backend_flag is not None else pk.wavefront_mode()
    mode = str(mode).lower()
    if mode in ("0", "false", "off", "no"):
        return False
    if mode not in ("auto", ""):
        return True
    from tpu_render_cluster.render.mesh import scene_mesh_set

    return pk.wavefront_eligible(scene_mesh_set(scene_name, frame))


def _mean_complement(histogram) -> float | None:
    count = 0
    total = 0.0
    for _key, series in histogram._series_items():
        count += series.count
        total += series.sum
    if count == 0:
        return None
    return 1.0 - total / count


def wasted_lane_fraction(registry=None) -> float | None:
    """1 - mean(alive fraction) over every recorded wavefront bounce.

    The average fraction of the ORIGINAL wavefront that is dead at each
    bounce launch — what a masked full-width bounce loop wastes, and
    what compaction reclaims. None before any wavefront render ran.
    """
    return _mean_complement(alive_fraction_histogram(registry))


def launched_wasted_lane_fraction(registry=None) -> float | None:
    """1 - mean(live / launched bucket) over every wavefront relaunch —
    the waste the wavefront driver itself still pays after the bucketed
    reclaim (block-quantized launches + the unfillable first bounce).
    None before any wavefront render ran."""
    return _mean_complement(launch_occupancy_histogram(registry))
