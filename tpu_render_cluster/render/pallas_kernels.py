"""Pallas TPU kernel for the render engine's hot op: nearest-hit intersection.

The path tracer spends its time in the rays x spheres intersection
(reference analog: the per-frame render loop inside Blender that
worker/src/rendering/runner/mod.rs shells out to; here the render engine is
TPU-native so the hot loop is ours to own). The XLA version in
``geometry.intersect_spheres`` materializes several [R, N] intermediates
between HBM-level fusions; this kernel fuses quadratic solve, validity
masking, and the min/argmin reduction into one VMEM-resident pass per ray
block.

Layout choices (see /opt/skills/guides/pallas_guide.md):
- rays ride the *lane* axis (128-wide) as [3, BLOCK_R] blocks; the sphere
  axis is the sublane axis, so the nearest-hit reduction is a sublane
  reduction producing [1, BLOCK_R];
- sphere data ([3, N] centers, [N, 1] radius^2 / |c|^2) is small enough to
  sit whole in VMEM for every grid step;
- the two contractions (d.c and o.c) are K=3 dot_generals on the MXU with
  ``preferred_element_type=float32``.

On non-TPU backends the kernel runs in interpret mode, so the same code
path is exercised by CPU tests.
"""

from __future__ import annotations

import functools
from typing import NamedTuple
from tpu_render_cluster.utils.env import env_int, env_str

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Plain Python floats: a jnp constant would be captured as a traced value,
# which pallas_call rejects.
INF = 1e30
EPS = 1e-3

# Rays per grid step. Swept on the real chip (bench.py, 256x256 4spp):
# 512 -> 432 f/s, 1024 -> 509, 2048 -> 538, 4096 -> 548, 8192 -> 545.
# Bigger blocks amortize per-step scheduling and keep the VPU busier;
# VMEM stays comfortable (the largest intermediate is [N_spheres, BLOCK_R]
# ~ 1 MB at 64 spheres).
BLOCK_R = 4096
# The BVH kernels use their own ray-block size: packet culling (the
# block-wide any() on AABB tests and the instance-level world-AABB skip)
# only bites when a block is spatially tight. Under the current
# single-grid-axis kernels (grid = ray blocks only; the per-block
# candidate-first instance sweep runs inside the kernel) the on-chip sweep
# favors 1024: smaller blocks are spatially tighter, so the seeded best-t
# and the top-level AABB skip cull more of the per-block instance sweep,
# and the walk's live-lane mask drains sooner. (The older two-axis
# rays x instances grid amortized per-step overhead differently and
# peaked at 2048 — that sweep read 1024 -> 16.1 f/s, 2048 -> 16.9,
# 4096 -> 16.7, 8192 -> 15.0; it no longer applies.)
BVH_BLOCK_R = 1024
_SUBLANE = 8  # f32 sublane tile; sphere count is padded to a multiple


def pallas_enabled() -> bool:
    """Whether intersect dispatches to the Pallas kernel.

    Default: only on a real TPU backend (interpret mode is a debugging
    path, much slower than XLA on CPU). ``TRC_PALLAS=1`` forces it on
    anywhere (tests use this); ``TRC_PALLAS=0`` disables it.

    Read at *trace* time: jitted callers bake the decision into their
    compiled executable, so flipping the env var mid-process has no effect
    on already-compiled functions (jax.clear_caches() to re-trace).
    """
    value = env_str("TRC_PALLAS")
    if value is None:
        return jax.default_backend() == "tpu"
    return value not in ("0", "false", "off")


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# Ray block for the per-bounce STATE-IO sphere kernel (the wavefront
# driver's sphere bounce). Deliberately smaller than BLOCK_R: the block
# size is also the bucket quantum of the wavefront compacted relaunch
# (render/compaction.py), so a 4096 block would make compaction a no-op
# below 4096 live rays; 1024 matches BVH_BLOCK_R's granularity.
SPHERE_BOUNCE_BLOCK_R = 1024


def wavefront_mode() -> str:
    """The ``TRC_WAVEFRONT`` env tier: ``off`` / ``auto`` / ``force``.

    - unset (``auto``): wavefront execution is used where it measured
      faster — deep-walk mesh scenes already on the per-bounce dispatch
      (``wavefront_eligible``);
    - ``TRC_WAVEFRONT=0`` (also ``false``/``off``): never;
    - ``TRC_WAVEFRONT=1`` (anything else truthy): force it for every
      Pallas-rendered scene, spheres included.

    Like ``TRC_PALLAS`` this is read when the dispatch decision is made
    (the wavefront driver runs outside jit, so per-frame, not per-trace).
    """
    value = (env_str("TRC_WAVEFRONT") or "").strip().lower()
    if value in ("", "auto"):
        return "auto"
    if value in ("0", "false", "off", "no"):
        return "off"
    return "force"


def wavefront_eligible(mesh) -> bool:
    """Auto-tier heuristic: scenes already on the per-bounce deep-walk
    dispatch — exactly where masked dead lanes still pay for BVH packet
    walks, which is the waste compaction removes. Shallow/megakernel
    scenes keep path state VMEM-resident across bounces; breaking the
    loop per bounce there costs more than compaction recovers."""
    return mesh is not None and not mesh_megakernel_eligible(mesh)


# (The combined should-this-scene-go-wavefront decision lives in
# render/compaction.wavefront_active — the single dispatch site, so the
# env tier, a backend override, and this heuristic can't be recombined
# differently by two callers.)


def tlas_enabled() -> bool:
    """Whether the mesh kernels traverse a two-level TLAS/BLAS hierarchy
    (default) or the flat per-instance sweep (``TRC_TLAS=0`` — the A/B
    baseline ``bench.py --bvh-compare`` measures against, and the on-chip
    triage kill switch).

    Read at *trace* time like ``TRC_PALLAS``: jitted renderers bake the
    decision, and the drivers additionally thread it as a static jit
    argument so both kernel variants can coexist in one process (the
    interleaved A/B bench relies on that).
    """
    value = env_str("TRC_TLAS")
    if value is None:
        return True
    return value not in ("0", "false", "off", "no")


def tlas_leaf_size() -> int:
    """Instances per TLAS leaf (``TRC_TLAS_LEAF``, default 4, clamped to
    [1, 16]). Part of the compiled kernel's identity — a distinct leaf
    size is a distinct trace."""
    leaf = env_int("TRC_TLAS_LEAF", 4)
    return max(1, min(leaf, 16))


def tlas_block_r() -> int:
    """Ray-block width of the TLAS kernel variants (``TRC_TLAS_BLOCK``,
    default 256).

    Packet pruning only exists at block granularity — a subtree is
    skipped when NO lane in the block wants it — so the TLAS walk wants
    much NARROWER packets than the flat sweep's ``BVH_BLOCK_R`` (1024,
    tuned for sweep-style launches where the block size only amortizes
    launch overhead). Measured on the CPU proxy (03-family, 48
    instances): 1024-lane packets union over most of the instance field
    and prune nothing (0.95x vs flat), 512 -> ~1.7x, 256 -> ~2x,
    128 -> ~2.3x but with more per-block overhead headroom on chip —
    256 is the default; re-tune on chip via the env knob. Snapped to a
    power of two in [128, BVH_BLOCK_R] so it always divides the pool
    width / bucket quanta the drivers round to, and read at trace time
    like the other TLAS knobs (part of each compiled kernel's shape).
    """
    raw = env_int("TRC_TLAS_BLOCK", 256)
    block = 128
    while block * 2 <= min(raw, BVH_BLOCK_R):
        block *= 2
    return block


def use_tlas_for(k_count: int, use_tlas: bool | None = None) -> bool:
    """Resolve the TLAS decision for a ``k_count``-instance field.

    ``None`` defers to the env tier. Fields that fit in one TLAS leaf
    degenerate to the flat sweep plus a root test — auto-disabled.
    """
    flag = tlas_enabled() if use_tlas is None else bool(use_tlas)
    return flag and k_count > tlas_leaf_size()


def bvh_quant_mode() -> int:
    """The ``TRC_BVH_QUANT`` env tier (default 0 = off): quantized node
    tables + packed carried ray state.

    - 0: fp32 slabs, int32 links, f32 carried state (the exact baseline);
    - 1: 16-bit fixed-point slabs (two per int32 word) + one packed meta
      word per node, bf16-packed carried throughput;
    - 2: 8-bit slabs (six per two words), same meta/state packing.

    Conservative outward rounding keeps every tier's IMAGES bit-identical
    on the masked tier (the quantized walk visits a superset of nodes;
    triangle tests stay exact f32 — see mesh.quantize_node_tables);
    wavefront/raypool additionally carry bf16 throughput, whose
    divergence budget tests/test_bvhq.py asserts. A static jit arg like
    ``TRC_TLAS``: read by untraced drivers/factories only (the
    ``env-tiers`` lint pass pins this) and threaded into every kernel
    identity, so distinct tiers coexist as distinct compiled programs in
    one process (the interleaved A/B bench).
    """
    return max(0, min(env_int("TRC_BVH_QUANT", 0), 2))


def resolve_bvh_quant(quant: int, *tables: tuple[int, int, int]) -> int:
    """Degrade the quant tier to 0 when any node table outgrows the
    packed meta word's ranges (``int32 -> int16/byte offsets where index
    ranges allow`` — ISSUE 15). Each table is (n_nodes, first_units,
    max_count); all limits are shape-derived, so the decision is static
    at trace time."""
    from tpu_render_cluster.render.mesh import (
        QUANT_MAX_COUNT,
        QUANT_MAX_FIRST_UNITS,
        QUANT_MAX_NODES,
    )

    if not quant:
        return 0
    for n_nodes, first_units, max_count in tables:
        # Skip links range over [0, n_nodes] INCLUSIVE (n_nodes is the
        # walk terminator), so the node count must stay strictly below
        # the 16-bit field's modulus or the terminator would wrap to 0
        # and the threaded walk would never end.
        if (
            n_nodes >= QUANT_MAX_NODES
            or first_units > QUANT_MAX_FIRST_UNITS
            or max_count > QUANT_MAX_COUNT
        ):
            return 0
    return max(0, min(int(quant), 2))


# ---------------------------------------------------------------------------
# Fused coherence sort key (ISSUE 10): the per-bounce re-sort key is
# computed in the mesh bounce kernels' EPILOGUE from the post-bounce ray
# state — one extra [1, BR] int32 output row — so the TLAS drivers'
# re-sort is a single argsort over a precomputed column instead of a
# separate XLA pass (candidate broadphase + quantization + dilation)
# over the full ray state. Layout (LSB -> MSB): direction octant [0:3),
# 5-bit/axis Morton cell of origin+direction [3:18), first-overlap
# candidate instance [18:24) (6 bits, clamped — packets that want the
# SAME instance first walk straight to its leaf and seed tight best-t),
# frame id [24:29) (pool kernels only; 0 elsewhere), dead flag bit 29.
# Always < 2^30, so the uint32 bit pattern bitcasts to a POSITIVE int32
# and a plain ascending argsort orders it exactly like the uint32 would.

KEY_DEAD_BIT = 29


def coherence_key_u32(
    px, py, pz, dx, dy, dz, dead, fid, candidate,
    lox, loy, loz, ivx, ivy, ivz,
):
    """The ONE key derivation, componentwise so the kernel epilogue
    ([1, BR] rows, SMEM scalar bounds) and the XLA twin ([R] columns,
    traced scalar bounds) provably compute bit-identical keys
    (tests/test_tlas.py pins it). ``p*`` = origin+direction components,
    ``dead`` bool, ``fid``/``candidate`` int32; ``lo*``/``iv*`` the
    quantization window scalars from ``mesh_key_bounds``. The candidate
    INPUT is derived per site with shared semantics (nearest-entry
    overlapped instance): the kernel epilogue walks the TLAS, the XLA
    twin runs ``instance_entry_candidates``."""
    from tpu_render_cluster.render.mesh import morton_dilate5

    def cell(p, lo, iv):
        quantized = jnp.clip((p - lo) * iv * 32.0, 0.0, 31.0)
        return quantized.astype(jnp.int32).astype(jnp.uint32)

    morton = (
        morton_dilate5(cell(px, lox, ivx))
        | (morton_dilate5(cell(py, loy, ivy)) << jnp.uint32(1))
        | (morton_dilate5(cell(pz, loz, ivz)) << jnp.uint32(2))
    )
    one = jnp.uint32(1)
    zero = jnp.uint32(0)
    octant = (
        jnp.where(dx > 0, one, zero)
        | (jnp.where(dy > 0, one, zero) << jnp.uint32(1))
        | (jnp.where(dz > 0, one, zero) << jnp.uint32(2))
    )
    cand_bits = jnp.minimum(candidate.astype(jnp.uint32), jnp.uint32(63))
    fid_bits = jnp.minimum(fid.astype(jnp.uint32), jnp.uint32(31))
    dead_bit = jnp.where(dead, one, zero) << jnp.uint32(KEY_DEAD_BIT)
    return (
        octant
        | (morton << jnp.uint32(3))
        | (cand_bits << jnp.uint32(18))
        | (fid_bits << jnp.uint32(24))
        | dead_bit
    )


def mesh_key_bounds(lo_w, hi_w):
    """Quantization window for the coherence key: the instance field's
    world AABB union, padded one unit (floor-bounce origins sit ON the
    field's boundary; escaped rays clamp to edge cells harmlessly).
    Returns ([3] lo, [3] 1/span) — frame-dependent only, never
    ray-dependent, so region and whole-frame launches key identically.
    """
    lo = jnp.min(lo_w, axis=0) - 1.0
    hi = jnp.max(hi_w, axis=0) + 1.0
    return lo, 1.0 / jnp.maximum(hi - lo, 1e-6)


def mesh_sort_keys(
    origins, directions, alive, key_lo, key_inv, fid=None, candidate=None,
):
    """XLA twin of the kernel epilogue's key ([R] int32): the INITIAL
    keys of a wavefront/deep-path/pool launch, before any bounce kernel
    has run to produce the fused column. ``candidate`` (optional [R]
    int32) is the nearest-entry overlapped instance from
    ``instance_entry_candidates``; None packs a neutral 0 (grouping by
    Morton/octant only)."""
    point = origins + directions
    if fid is None:
        fid = jnp.zeros(origins.shape[0], jnp.int32)
    if candidate is None:
        candidate = jnp.zeros(origins.shape[0], jnp.int32)
    key = coherence_key_u32(
        point[:, 0], point[:, 1], point[:, 2],
        directions[:, 0], directions[:, 1], directions[:, 2],
        ~alive, fid, candidate,
        key_lo[0], key_lo[1], key_lo[2],
        key_inv[0], key_inv[1], key_inv[2],
    )
    return key.astype(jnp.int32)


def initial_mesh_sort_keys(mesh, origins, directions, alive):
    """Bounce-0 coherence keys for a TLAS launch, derived from the
    MeshSet: instance world AABBs -> quantization window + nearest-entry
    candidates -> ``mesh_sort_keys``. THE one site both the deep
    per-bounce path (integrator.trace_paths) and the wavefront driver
    (compaction._initial_mesh_keys) key bounce 0 through, so the two
    tiers' initial sorts cannot drift from each other or from the kernel
    epilogue's fused column (bit-identical on live lanes, pinned by
    tests/test_tlas.py)."""
    from tpu_render_cluster.render.mesh import instance_morton_order

    table = _instance_table(
        mesh.instances.rotation, mesh.instances.translation,
        mesh.instances.scale, mesh.bvh.bounds_min, mesh.bvh.bounds_max,
    )
    lo_w, hi_w = table[:, 13:16], table[:, 16:19]
    # Candidates are SLOT labels (the Morton-sorted order the kernels'
    # instance table uses), not original-index labels — the epilogue's
    # entry walk reports slots, and slot-adjacent == spatially-adjacent
    # is the grouping the packet cull is tuned for.
    order = instance_morton_order(lo_w, hi_w)
    lo_s, hi_s = lo_w[order], hi_w[order]
    key_lo, key_inv = mesh_key_bounds(lo_s, hi_s)
    return mesh_sort_keys(
        origins, directions, alive, key_lo, key_inv,
        candidate=instance_entry_candidates(origins, directions, lo_s, hi_s),
    )


# ---------------------------------------------------------------------------
# Packed carried ray state (ISSUE 15, quant tiers >= 1): the wavefront
# driver re-buckets and the ray pool permutes the FULL carried tuple every
# bounce/iteration — the throughput column is pure shading state with no
# traversal role, so it rides as bf16 packed two-per-f32-word (12 -> 8
# carried bytes per lane, one fewer gather column). The pack/unpack pair
# must be exact inverses; the f32->bf16 round-trip per carry step is the
# divergence the masked-vs-packed budget in tests/test_bvhq.py bounds.


def pack_throughput_bf16(throughput):
    """[R, 3] f32 -> [R, 2] f32 words carrying 4 bf16 lanes (one pad)."""
    half = jnp.concatenate(
        [
            throughput.astype(jnp.bfloat16),
            jnp.zeros((throughput.shape[0], 1), jnp.bfloat16),
        ],
        axis=1,
    )
    return jax.lax.bitcast_convert_type(
        half.reshape(-1, 2, 2), jnp.float32
    )


def unpack_throughput_bf16(packed):
    """Inverse of ``pack_throughput_bf16``: [R, 2] f32 -> [R, 3] f32."""
    half = jax.lax.bitcast_convert_type(packed, jnp.bfloat16)
    return half.reshape(packed.shape[0], 4)[:, :3].astype(jnp.float32)


# Pool meta word (quant tiers >= 1): fid [0:8), bounce [8:16), dead bit 16
# — one int32 column replacing the pool's separate alive/fid/bounce
# carried columns (the alive column is DROPPED: it is the meta dead bit).
POOL_META_DEAD_BIT = 16


def pack_pool_meta(fid, bounce, alive):
    return (
        fid.astype(jnp.int32)
        | (bounce.astype(jnp.int32) << 8)
        | jnp.where(alive, 0, 1 << POOL_META_DEAD_BIT)
    )


def unpack_pool_meta(meta):
    """(fid, bounce, alive) from the packed pool meta column."""
    return (
        meta & 0xFF,
        (meta >> 8) & 0xFF,
        (meta >> POOL_META_DEAD_BIT) & 1 == 0,
    )


def _nearest_hit_kernel(o_ref, d_ref, c_ref, r2_ref, csq_ref, t_ref, idx_ref):
    """One ray block vs all spheres; writes min-t and argmin index."""
    o = o_ref[:, :]  # [3, BR]
    d = d_ref[:, :]  # [3, BR]
    c = c_ref[:, :]  # [3, N]
    contract_first = (((0,), (0,)), ((), ()))
    # [N, BR] contractions on the MXU.
    dc = jax.lax.dot_general(c, d, contract_first, preferred_element_type=jnp.float32)
    oc = jax.lax.dot_general(c, o, contract_first, preferred_element_type=jnp.float32)
    od = jnp.sum(o * d, axis=0, keepdims=True)  # [1, BR]
    o_sq = jnp.sum(o * o, axis=0, keepdims=True)  # [1, BR]

    r2 = r2_ref[:, :]  # [N, 1]
    oc_dot_d = dc - od  # d . (c - o)
    oc_sq = o_sq - 2.0 * oc + csq_ref[:, :]  # |o - c|^2
    disc = oc_dot_d * oc_dot_d - (oc_sq - r2)
    valid = (disc > 0.0) & (r2 > 0.0)
    sqrt_disc = jnp.sqrt(jnp.maximum(disc, 0.0))
    t0 = oc_dot_d - sqrt_disc
    t1 = oc_dot_d + sqrt_disc
    t = jnp.where(t0 > EPS, t0, jnp.where(t1 > EPS, t1, INF))
    t = jnp.where(valid, t, INF)  # [N, BR]

    n = t.shape[0]
    t_min = jnp.min(t, axis=0, keepdims=True)  # [1, BR]
    lanes = jax.lax.broadcasted_iota(jnp.int32, t.shape, 0)
    # First index attaining the min (matches jnp.argmin tie-breaking).
    idx = jnp.min(jnp.where(t == t_min, lanes, n), axis=0, keepdims=True)
    t_ref[:, :] = t_min
    idx_ref[:, :] = jnp.minimum(idx, n - 1)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _nearest_hit(origins, directions, centers, radii, *, interpret: bool):
    rays = origins.shape[0]
    padded_rays = -(-rays // BLOCK_R) * BLOCK_R
    ray_pad = padded_rays - rays
    o_t = jnp.pad(origins, ((0, ray_pad), (0, 0))).T  # [3, Rp]
    d_t = jnp.pad(directions, ((0, ray_pad), (0, 0))).T  # [3, Rp]

    n = centers.shape[0]
    padded_n = -(-n // _SUBLANE) * _SUBLANE
    sphere_pad = padded_n - n
    c_t = jnp.pad(centers, ((0, sphere_pad), (0, 0))).T  # [3, Np]
    radii = jnp.pad(radii, (0, sphere_pad))
    r2 = (radii * radii)[:, None]  # [Np, 1]
    csq = jnp.sum(c_t * c_t, axis=0)[:, None]  # [Np, 1]

    grid = (padded_rays // BLOCK_R,)
    t, idx = pl.pallas_call(
        _nearest_hit_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((3, BLOCK_R), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((3, BLOCK_R), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((3, padded_n), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((padded_n, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((padded_n, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, BLOCK_R), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, BLOCK_R), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, padded_rays), jnp.float32),
            jax.ShapeDtypeStruct((1, padded_rays), jnp.int32),
        ],
        interpret=interpret,
    )(o_t, d_t, c_t, r2, csq)
    return t[0, :rays], idx[0, :rays]


def intersect_spheres_pallas(scene, origins, directions):
    """Drop-in Pallas replacement for ``geometry.intersect_spheres``.

    Returns (t [R] float32 with INF misses, index [R] int32).
    """
    # Padded ray slots (zero origin/direction) produce harmless garbage that
    # the wrapper slices off; padded sphere slots have r2 == 0 -> never hit.
    t, idx = _nearest_hit(
        origins, directions, scene.centers, scene.radii, interpret=_interpret()
    )
    # Padded sphere indices can only appear for all-miss rays (t == INF);
    # clamp into range like the jnp argmin would.
    return t, jnp.minimum(idx, scene.centers.shape[0] - 1)


def _any_hit_kernel(o_ref, d_ref, c_ref, r2_ref, csq_ref, hit_ref):
    """Shadow query: does ANY sphere intersect the ray (t > EPS)?

    Same quadratic solve as _nearest_hit_kernel but no argmin and no min-t:
    the reduction is a single boolean OR over the sublane (sphere) axis —
    about a third less VMEM traffic per block than the nearest-hit pass.
    """
    o = o_ref[:, :]  # [3, BR]
    d = d_ref[:, :]  # [3, BR]
    c = c_ref[:, :]  # [3, N]
    contract_first = (((0,), (0,)), ((), ()))
    dc = jax.lax.dot_general(c, d, contract_first, preferred_element_type=jnp.float32)
    oc = jax.lax.dot_general(c, o, contract_first, preferred_element_type=jnp.float32)
    od = jnp.sum(o * d, axis=0, keepdims=True)
    o_sq = jnp.sum(o * o, axis=0, keepdims=True)

    r2 = r2_ref[:, :]
    oc_dot_d = dc - od
    oc_sq = o_sq - 2.0 * oc + csq_ref[:, :]
    disc = oc_dot_d * oc_dot_d - (oc_sq - r2)
    valid = (disc > 0.0) & (r2 > 0.0)
    sqrt_disc = jnp.sqrt(jnp.maximum(disc, 0.0))
    # Hit iff the far root is in front and the near root isn't past EPS
    # behind us: equivalent to (t0 > EPS) | (t1 > EPS) with t = min valid.
    t1 = oc_dot_d + sqrt_disc
    hit = valid & (t1 > EPS)
    hit_ref[:, :] = jnp.max(
        jnp.where(hit, 1.0, 0.0), axis=0, keepdims=True
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def _any_hit(origins, directions, centers, radii, *, interpret: bool):
    rays = origins.shape[0]
    padded_rays = -(-rays // BLOCK_R) * BLOCK_R
    ray_pad = padded_rays - rays
    o_t = jnp.pad(origins, ((0, ray_pad), (0, 0))).T
    d_t = jnp.pad(directions, ((0, ray_pad), (0, 0))).T

    n = centers.shape[0]
    padded_n = -(-n // _SUBLANE) * _SUBLANE
    sphere_pad = padded_n - n
    c_t = jnp.pad(centers, ((0, sphere_pad), (0, 0))).T
    radii = jnp.pad(radii, (0, sphere_pad))
    r2 = (radii * radii)[:, None]
    csq = jnp.sum(c_t * c_t, axis=0)[:, None]

    grid = (padded_rays // BLOCK_R,)
    hit = pl.pallas_call(
        _any_hit_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((3, BLOCK_R), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((3, BLOCK_R), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((3, padded_n), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((padded_n, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((padded_n, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, BLOCK_R), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_shape=[jax.ShapeDtypeStruct((1, padded_rays), jnp.float32)],
        interpret=interpret,
    )(o_t, d_t, c_t, r2, csq)[0]
    return hit[0, :rays] > 0.5


def occluded_pallas(scene, origins, directions):
    """Any-hit shadow query (Pallas). Matches ``geometry.occluded`` for the
    sun case: unbounded max_t, plane excluded."""
    return _any_hit(
        origins, directions, scene.centers, scene.radii, interpret=_interpret()
    )


# ---------------------------------------------------------------------------
# Fused path-trace megakernel: the WHOLE bounce loop in one pallas_call.
#
# The per-bounce XLA pipeline round-trips the path state (origins,
# directions, throughput, radiance, alive — ~5 x [R, 3] f32) through HBM on
# every bounce, which makes the tracer HBM-bound once intersection runs in
# VMEM. This kernel keeps the state resident in VMEM for a block of rays
# across ALL bounces: rays are read once, radiance is written once, and the
# per-bounce sphere pass ([N, BR] intermediates), shading, shadow test, and
# cosine resampling never touch HBM. RNG is a counter-based PCG hash of
# (global ray index, bounce, stream) — no sequential state, so any ray
# block computes identically regardless of grid position or device.


def _pcg_hash(x):
    """PCG output permutation on uint32 (Jarzynski & Olano, GPU RNG survey)."""
    state = x * jnp.uint32(747796405) + jnp.uint32(2891336453)
    shift = (state >> jnp.uint32(28)) + jnp.uint32(4)
    word = ((state >> shift) ^ state) * jnp.uint32(277803737)
    return (word >> jnp.uint32(22)) ^ word


def _uniform_from_hash(h):
    """uint32 -> float32 in [0, 1) using the top 24 bits.

    Mosaic has no uint32->float32 convert rule; the 24-bit word is
    value-preserved by a same-width bitcast to int32 (it is < 2^31), and
    int32->float32 is a supported convert.
    """
    word = jax.lax.bitcast_convert_type(h >> jnp.uint32(8), jnp.int32)
    return word.astype(jnp.float32) * jnp.float32(1.0 / (1 << 24))


def _trace_kernel_factory(
    max_bounces: int, n_padded: int, state_io: bool = False,
    pool_io: bool = False, lane_io: bool = False,
):
    """Sphere path-trace kernel. Three shapes share one bounce_step (same
    split as _mesh_trace_kernel_factory):

    - state_io=False: the whole-bounce-loop MEGAKERNEL (state
      VMEM-resident across all bounces, radiance out);
    - state_io=True: ONE bounce per launch with path state streamed
      in/out plus a per-lane ORIGINAL lane id (the RNG counter, so
      streams survive compaction/re-sorting) and a live-count scalar
      (blocks whose first lane is past it — all dead by the compaction
      contract — skip the bounce entirely). ``max_bounces`` still names
      the TOTAL bounce count so RNG counters match the megakernel.
    - pool_io=True: the device-resident ray-pool shape
      (render/raypool.py). Like state_io but lanes from DIFFERENT
      frames share one launch, so the scalar seed/bounce become
      per-lane rows (seed = the lane's frame seed, bounce = the lane's
      own depth — together with the original lane id they reproduce the
      masked loop's (frame, lane, bounce) RNG stream exactly), the
      sphere arrays are a multi-frame STACK with a per-sphere frame-id
      column, and every intersection (nearest + shadow) is masked to
      spheres whose frame id matches the lane's carried frame id — a
      lane only ever sees its own frame's geometry.
    """
    contract_first = (((0,), (0,)), ((), ()))

    def kernel(*refs):
        if pool_io:
            (live_ref, o_ref, d_ref, thr_ref, alive_ref, lane_ref,
             seed_row_ref, bounce_row_ref, fid_row_ref,
             c_ref, r2_ref, csq_ref, rad_ref,
             albedo_ref, emission_ref, dcsun_ref, sfid_ref, params_ref,
             out_ref, o_out_ref, d_out_ref, thr_out_ref,
             alive_out_ref) = refs
        elif state_io:
            (seed_ref, bounce_ref, live_ref, o_ref, d_ref, thr_ref,
             alive_ref, lane_ref, c_ref, r2_ref, csq_ref, rad_ref,
             albedo_ref, emission_ref, dcsun_ref, params_ref,
             out_ref, o_out_ref, d_out_ref, thr_out_ref,
             alive_out_ref) = refs
        elif lane_io:
            # The megakernel with an EXPLICIT lane row: the cluster-tile
            # region path feeds each ray its full-frame lane id, so a
            # cropped launch runs bitwise-identical per-lane math to the
            # whole-frame megakernel (same kernel, same loop — only the
            # RNG counter's source differs).
            (seed_ref, o_ref, d_ref, lane_ref, c_ref, r2_ref, csq_ref,
             rad_ref, albedo_ref, emission_ref, dcsun_ref, params_ref,
             out_ref) = refs
        else:
            (seed_ref, o_ref, d_ref, c_ref, r2_ref, csq_ref, rad_ref,
             albedo_ref, emission_ref, dcsun_ref, params_ref,
             out_ref) = refs
        o = o_ref[:, :]  # [3, BR] ray origins
        d = d_ref[:, :]  # [3, BR] ray directions
        c = c_ref[:, :]  # [3, N] sphere centers
        r2 = r2_ref[:, :]  # [N, 1] radius^2 (0 for padding -> never hits)
        csq = csq_ref[:, :]  # [N, 1] |c|^2
        radius = rad_ref[:, :]  # [N, 1]
        albedo_t = albedo_ref[:, :]  # [3, N]
        emission_t = emission_ref[:, :]  # [3, N]
        dc_sun = dcsun_ref[:, :]  # [N, 1] c . sun
        # params rows: 0 sun_dir, 1 sun_color, 2 sky_horizon, 3 sky_zenith,
        # 4 plane_albedo_a, 5 plane_albedo_b   (each [1, 3] -> column vecs)
        params = params_ref[:, :]  # [8, 3]
        sun = params[0:1, :].T  # [3, 1]
        sun_color = params[1:2, :].T
        sky_horizon = params[2:3, :].T
        sky_zenith = params[3:4, :].T
        plane_a = params[4:5, :].T
        plane_b = params[5:6, :].T

        block = o.shape[1]
        if pool_io:
            # Per-lane seed: lanes carry their FRAME's trace seed, so a
            # ray's stream matches the masked single-frame loop bit for
            # bit wherever the pool's permutation/refill lands it.
            seed = seed_row_ref[:, :].astype(jnp.uint32)  # [1, BR]
            ray_index = lane_ref[:, :].astype(jnp.uint32)
            # Frame mask: a lane only intersects spheres whose stacked
            # frame id matches its own ([N, 1] == [1, BR] -> [N, BR]).
            fid_match = sfid_ref[:, :] == fid_row_ref[:, :]
        else:
            seed = seed_ref[0, 0].astype(jnp.uint32)
            fid_match = None
            if state_io or lane_io:
                # RNG counters follow the ORIGINAL lane id the caller
                # threads through compaction/re-sorts (or the region
                # path's full-frame lane map), not the current position:
                # a ray keeps its stream wherever it lands.
                ray_index = lane_ref[:, :].astype(jnp.uint32)
            else:
                ray_index = (
                    jax.lax.broadcasted_iota(
                        jnp.int32, (1, block), 1
                    ).astype(jnp.uint32)
                    + jnp.uint32(pl.program_id(0) * block)
                )
        sphere_iota = jax.lax.broadcasted_iota(jnp.int32, (n_padded, block), 0)

        throughput = jnp.ones((3, block), jnp.float32)
        radiance = jnp.zeros((3, block), jnp.float32)
        alive = jnp.ones((1, block), jnp.float32)

        def bounce_step(bounce, carry):
            o, d, throughput, radiance, alive = carry
            # -- nearest sphere hit (same math as _nearest_hit_kernel) ----
            dc = jax.lax.dot_general(
                c, d, contract_first, preferred_element_type=jnp.float32
            )
            oc = jax.lax.dot_general(
                c, o, contract_first, preferred_element_type=jnp.float32
            )
            od = jnp.sum(o * d, axis=0, keepdims=True)
            o_sq = jnp.sum(o * o, axis=0, keepdims=True)
            oc_dot_d = dc - od
            oc_sq = o_sq - 2.0 * oc + csq
            disc = oc_dot_d * oc_dot_d - (oc_sq - r2)
            valid = (disc > 0.0) & (r2 > 0.0)
            if fid_match is not None:
                valid = valid & fid_match
            sqrt_disc = jnp.sqrt(jnp.maximum(disc, 0.0))
            t0 = oc_dot_d - sqrt_disc
            t1 = oc_dot_d + sqrt_disc
            t_all = jnp.where(t0 > EPS, t0, jnp.where(t1 > EPS, t1, INF))
            t_all = jnp.where(valid, t_all, INF)  # [N, BR]
            t_sphere = jnp.min(t_all, axis=0, keepdims=True)  # [1, BR]
            idx = jnp.min(
                jnp.where(t_all == t_sphere, sphere_iota, n_padded),
                axis=0,
                keepdims=True,
            )
            idx = jnp.minimum(idx, n_padded - 1)

            # -- ground plane y = 0 ---------------------------------------
            d_y = d[1:2, :]
            o_y = o[1:2, :]
            denom = jnp.where(jnp.abs(d_y) < 1e-8, 1e-8, d_y)
            t_plane = -o_y / denom
            t_plane = jnp.where(
                (t_plane > EPS) & (jnp.abs(d_y) >= 1e-8), t_plane, INF
            )
            is_plane = (t_plane < t_sphere).astype(jnp.float32)  # [1, BR]
            t = jnp.minimum(t_sphere, t_plane)
            hit = (t < INF).astype(jnp.float32)

            # -- sky on escape --------------------------------------------
            blend = jnp.clip(d[1:2, :], 0.0, 1.0)
            sun_cos_dir = jnp.sum(d * sun, axis=0, keepdims=True)
            sun_disc = jnp.where(sun_cos_dir > 0.9995, 8.0, 0.0)
            sky = (1.0 - blend) * sky_horizon + blend * sky_zenith
            sky = sky + sun_disc * sun_color
            radiance = radiance + throughput * sky * (alive * (1.0 - hit))

            alive = alive * hit
            p = o + d * t  # [3, BR]

            # -- gathers as one-hot matmuls (N is small, MXU-friendly) ----
            one_hot = (sphere_iota == idx).astype(jnp.float32)  # [N, BR]
            c_hit = jax.lax.dot_general(
                c, one_hot, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [3, BR]
            r_hit = jnp.sum(radius * one_hot, axis=0, keepdims=True)  # [1, BR]
            albedo_hit = jax.lax.dot_general(
                albedo_t, one_hot, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            emission_hit = jax.lax.dot_general(
                emission_t, one_hot, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

            sphere_normal = (p - c_hit) / jnp.maximum(r_hit, 1e-6)
            plane_normal = jnp.concatenate(
                [
                    jnp.zeros((1, block), jnp.float32),
                    jnp.ones((1, block), jnp.float32),
                    jnp.zeros((1, block), jnp.float32),
                ],
                axis=0,
            )
            normal = is_plane * plane_normal + (1.0 - is_plane) * sphere_normal

            checker = (
                jnp.floor(p[0:1, :]).astype(jnp.int32)
                + jnp.floor(p[2:3, :]).astype(jnp.int32)
            ) % 2
            checker_rgb = jnp.where(checker == 0, plane_a, plane_b)
            albedo = is_plane * checker_rgb + (1.0 - is_plane) * albedo_hit
            emission = (1.0 - is_plane) * emission_hit
            radiance = radiance + throughput * emission * alive

            # -- sun NEE: one any-hit shadow dot (sun dir is uniform) -----
            shadow_o = p + normal * (EPS * 4.0)
            oc_s = jax.lax.dot_general(
                c, shadow_o, contract_first, preferred_element_type=jnp.float32
            )
            od_s = jnp.sum(shadow_o * sun, axis=0, keepdims=True)
            osq_s = jnp.sum(shadow_o * shadow_o, axis=0, keepdims=True)
            ocd_s = dc_sun - od_s
            ocsq_s = osq_s - 2.0 * oc_s + csq
            disc_s = ocd_s * ocd_s - (ocsq_s - r2)
            valid_s = (disc_s > 0.0) & (r2 > 0.0)
            if fid_match is not None:
                valid_s = valid_s & fid_match
            t1_s = ocd_s + jnp.sqrt(jnp.maximum(disc_s, 0.0))
            shadowed = jnp.max(
                jnp.where(valid_s & (t1_s > EPS), 1.0, 0.0),
                axis=0,
                keepdims=True,
            )
            cos_sun = jnp.maximum(jnp.sum(normal * sun, axis=0, keepdims=True), 0.0)
            direct = (
                albedo * sun_color * (cos_sun * (1.0 - shadowed) * alive)
                / jnp.float32(jnp.pi)
            )
            radiance = radiance + throughput * direct

            # -- continue the path: cosine-weighted resample --------------
            throughput = throughput * (alive * albedo + (1.0 - alive))
            counter = ray_index * jnp.uint32(2 * max_bounces + 2) + jnp.uint32(2) * bounce.astype(jnp.uint32)
            u1 = _uniform_from_hash(_pcg_hash(counter ^ seed))
            u2 = _uniform_from_hash(_pcg_hash((counter + jnp.uint32(1)) ^ seed))
            r = jnp.sqrt(u1)
            phi = jnp.float32(2.0 * jnp.pi) * u2
            x = r * jnp.cos(phi)
            y = r * jnp.sin(phi)
            z = jnp.sqrt(jnp.maximum(0.0, 1.0 - u1))
            helper_x = jnp.where(jnp.abs(normal[0:1, :]) > 0.9, 0.0, 1.0)
            helper_y = 1.0 - helper_x
            # tangent = helper x normal (helper is (hx, hy, 0))
            tx = helper_y * normal[2:3, :]
            ty = -helper_x * normal[2:3, :]
            tz = helper_x * normal[1:2, :] - helper_y * normal[0:1, :]
            tangent = jnp.concatenate([tx, ty, tz], axis=0)
            tangent = tangent / jnp.maximum(
                jnp.sqrt(jnp.sum(tangent * tangent, axis=0, keepdims=True)), 1e-8
            )
            # bitangent = normal x tangent
            bx = normal[1:2, :] * tangent[2:3, :] - normal[2:3, :] * tangent[1:2, :]
            by = normal[2:3, :] * tangent[0:1, :] - normal[0:1, :] * tangent[2:3, :]
            bz = normal[0:1, :] * tangent[1:2, :] - normal[1:2, :] * tangent[0:1, :]
            bitangent = jnp.concatenate([bx, by, bz], axis=0)
            new_d = x * tangent + y * bitangent + z * normal
            new_o = p + normal * (EPS * 4.0)
            # where-select (not multiply-mask): dead lanes keep their old
            # finite state, so no inf*0 can poison later bounces.
            live = alive > 0.5
            o = jnp.where(live, new_o, o)
            d = jnp.where(live, new_d, d)
            return (o, d, throughput, radiance, alive)

        if state_io or pool_io:
            # ONE bounce with streamed state. Blocks entirely past the
            # live count are all-dead (the compaction contract sorts dead
            # lanes to the tail) and pass their state through untouched —
            # exactly what the masked bounce computes for dead lanes, for
            # free. In pool mode the bounce index is a per-lane row (the
            # pool mixes depths); it only feeds the RNG counter, which is
            # per-lane arithmetic either way.
            throughput = thr_ref[:, :]
            alive = alive_ref[:, :]
            bounce_value = (
                bounce_row_ref[:, :] if pool_io else bounce_ref[0, 0]
            )
            block_start = pl.program_id(0) * block
            o, d, throughput, radiance, alive = jax.lax.cond(
                block_start < live_ref[0, 0],
                lambda: bounce_step(
                    bounce_value, (o, d, throughput, radiance, alive)
                ),
                lambda: (o, d, throughput, radiance, alive),
            )
            out_ref[:, :] = radiance
            o_out_ref[:, :] = o
            d_out_ref[:, :] = d
            thr_out_ref[:, :] = throughput
            alive_out_ref[:, :] = alive
        else:
            _, _, _, radiance, _ = jax.lax.fori_loop(
                0, max_bounces, bounce_step,
                (o, d, throughput, radiance, alive),
            )
            out_ref[:, :] = radiance

    return kernel


@functools.partial(jax.jit, static_argnames=("max_bounces", "interpret"))
def _trace_fused(
    origins, directions, centers, radii, albedo, emission,
    sun_direction, sun_color, sky_horizon, sky_zenith,
    plane_albedo_a, plane_albedo_b, seed,
    *, max_bounces: int, interpret: bool, lane=None,
):
    rays = origins.shape[0]
    padded_rays = -(-rays // BLOCK_R) * BLOCK_R
    ray_pad = padded_rays - rays
    o_t = jnp.pad(origins, ((0, ray_pad), (0, 0))).T
    d_t = jnp.pad(directions, ((0, ray_pad), (0, 0))).T
    lane_t = (
        None
        if lane is None
        else jnp.pad(jnp.asarray(lane, jnp.int32), (0, ray_pad))[None, :]
    )

    n = centers.shape[0]
    padded_n = -(-n // _SUBLANE) * _SUBLANE
    sphere_pad = padded_n - n
    c_t = jnp.pad(centers, ((0, sphere_pad), (0, 0))).T  # [3, Np]
    radii_p = jnp.pad(radii, (0, sphere_pad))
    r2 = (radii_p * radii_p)[:, None]
    csq = jnp.sum(c_t * c_t, axis=0)[:, None]
    rad = radii_p[:, None]
    albedo_t = jnp.pad(albedo, ((0, sphere_pad), (0, 0))).T
    emission_t = jnp.pad(emission, ((0, sphere_pad), (0, 0))).T
    dc_sun = (c_t.T @ sun_direction)[:, None]  # [Np, 1]

    params = jnp.zeros((8, 3), jnp.float32)
    params = params.at[0].set(sun_direction)
    params = params.at[1].set(sun_color)
    params = params.at[2].set(sky_horizon)
    params = params.at[3].set(sky_zenith)
    params = params.at[4].set(plane_albedo_a)
    params = params.at[5].set(plane_albedo_b)
    seed_arr = jnp.asarray(seed, jnp.int32).reshape(1, 1)

    grid = (padded_rays // BLOCK_R,)
    whole = lambda i: (0, 0)  # noqa: E731 - scene blocks replicated per step
    ray_block = pl.BlockSpec((3, BLOCK_R), lambda i: (0, i), memory_space=pltpu.VMEM)
    lane_block = pl.BlockSpec((1, BLOCK_R), lambda i: (0, i), memory_space=pltpu.VMEM)
    in_specs = [
        pl.BlockSpec((1, 1), whole, memory_space=pltpu.SMEM),
        ray_block,
        ray_block,
        *([lane_block] if lane_t is not None else []),
        pl.BlockSpec((3, padded_n), whole, memory_space=pltpu.VMEM),
        pl.BlockSpec((padded_n, 1), whole, memory_space=pltpu.VMEM),
        pl.BlockSpec((padded_n, 1), whole, memory_space=pltpu.VMEM),
        pl.BlockSpec((padded_n, 1), whole, memory_space=pltpu.VMEM),
        pl.BlockSpec((3, padded_n), whole, memory_space=pltpu.VMEM),
        pl.BlockSpec((3, padded_n), whole, memory_space=pltpu.VMEM),
        pl.BlockSpec((padded_n, 1), whole, memory_space=pltpu.VMEM),
        pl.BlockSpec((8, 3), whole, memory_space=pltpu.VMEM),
    ]
    operands = [seed_arr, o_t, d_t]
    if lane_t is not None:
        operands.append(lane_t)
    operands += [c_t, r2, csq, rad, albedo_t, emission_t, dc_sun, params]
    out = pl.pallas_call(
        _trace_kernel_factory(max_bounces, padded_n, lane_io=lane_t is not None),
        grid=grid,
        in_specs=in_specs,
        out_specs=[ray_block],
        out_shape=[jax.ShapeDtypeStruct((3, padded_rays), jnp.float32)],
        interpret=interpret,
    )(*operands)[0]
    return out.T[:rays]


def trace_paths_fused(
    scene, origins, directions, seed, *, max_bounces: int, lane=None
):
    """Fused megakernel path trace; drop-in for integrator.trace_paths.

    ``seed`` is an int32 scalar (derived from the frame/tile) driving the
    in-kernel counter-based PCG RNG; radiance is returned as [R, 3].
    ``lane`` (optional [R] int32) overrides the positional RNG counters —
    the cluster-tile region path passes full-frame lane ids so a cropped
    launch reproduces the whole-frame image bitwise on its pixels.
    """
    return _trace_fused(
        origins,
        directions,
        scene.centers,
        scene.radii,
        scene.albedo,
        scene.emission,
        scene.sun_direction,
        scene.sun_color,
        scene.sky_horizon,
        scene.sky_zenith,
        scene.plane_albedo_a,
        scene.plane_albedo_b,
        seed,
        max_bounces=max_bounces,
        interpret=_interpret(),
        lane=lane,
    )


@functools.partial(jax.jit, static_argnames=("total_bounces", "interpret"))
def _sphere_bounce(
    origins, directions, throughput, alive, lane, live_count, seed, bounce,
    centers, radii, albedo, emission,
    sun_direction, sun_color, sky_horizon, sky_zenith,
    plane_albedo_a, plane_albedo_b,
    *, total_bounces: int, interpret: bool,
):
    rays = origins.shape[0]
    block = SPHERE_BOUNCE_BLOCK_R
    padded_rays = -(-rays // block) * block
    ray_pad = padded_rays - rays
    # Zero pad is fine here (unlike the BVH kernels): the sphere pass has
    # no cross-lane packet culling, and pad lanes arrive DEAD (alive pad
    # 0) so their garbage t never reaches an output.
    o_t = jnp.pad(origins, ((0, ray_pad), (0, 0))).T
    d_t = jnp.pad(directions, ((0, ray_pad), (0, 0))).T
    thr_t = jnp.pad(throughput, ((0, ray_pad), (0, 0))).T
    alive_t = jnp.pad(alive.astype(jnp.float32), (0, ray_pad))[None, :]
    lane_t = jnp.pad(lane.astype(jnp.int32), (0, ray_pad))[None, :]

    n = centers.shape[0]
    padded_n = -(-n // _SUBLANE) * _SUBLANE
    sphere_pad = padded_n - n
    c_t = jnp.pad(centers, ((0, sphere_pad), (0, 0))).T
    radii_p = jnp.pad(radii, (0, sphere_pad))
    r2 = (radii_p * radii_p)[:, None]
    csq = jnp.sum(c_t * c_t, axis=0)[:, None]
    rad = radii_p[:, None]
    albedo_t = jnp.pad(albedo, ((0, sphere_pad), (0, 0))).T
    emission_t = jnp.pad(emission, ((0, sphere_pad), (0, 0))).T
    dc_sun = (c_t.T @ sun_direction)[:, None]

    params = jnp.zeros((8, 3), jnp.float32)
    params = params.at[0].set(sun_direction)
    params = params.at[1].set(sun_color)
    params = params.at[2].set(sky_horizon)
    params = params.at[3].set(sky_zenith)
    params = params.at[4].set(plane_albedo_a)
    params = params.at[5].set(plane_albedo_b)
    seed_arr = jnp.asarray(seed, jnp.int32).reshape(1, 1)
    bounce_arr = jnp.asarray(bounce, jnp.int32).reshape(1, 1)
    live_arr = jnp.asarray(live_count, jnp.int32).reshape(1, 1)

    grid = (padded_rays // block,)
    whole = lambda i: (0, 0)  # noqa: E731
    ray_block = pl.BlockSpec((3, block), lambda i: (0, i), memory_space=pltpu.VMEM)
    row_block = pl.BlockSpec((1, block), lambda i: (0, i), memory_space=pltpu.VMEM)
    contrib, o2, d2, thr2, alive2 = pl.pallas_call(
        _trace_kernel_factory(total_bounces, padded_n, state_io=True),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1), whole, memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), whole, memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), whole, memory_space=pltpu.SMEM),
            ray_block,
            ray_block,
            ray_block,
            row_block,
            row_block,
            pl.BlockSpec((3, padded_n), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((padded_n, 1), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((padded_n, 1), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((padded_n, 1), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((3, padded_n), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((3, padded_n), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((padded_n, 1), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((8, 3), whole, memory_space=pltpu.VMEM),
        ],
        out_specs=[ray_block, ray_block, ray_block, ray_block, row_block],
        out_shape=[
            jax.ShapeDtypeStruct((3, padded_rays), jnp.float32),
            jax.ShapeDtypeStruct((3, padded_rays), jnp.float32),
            jax.ShapeDtypeStruct((3, padded_rays), jnp.float32),
            jax.ShapeDtypeStruct((3, padded_rays), jnp.float32),
            jax.ShapeDtypeStruct((1, padded_rays), jnp.float32),
        ],
        interpret=interpret,
    )(seed_arr, bounce_arr, live_arr, o_t, d_t, thr_t, alive_t, lane_t,
      c_t, r2, csq, rad, albedo_t, emission_t, dc_sun, params)
    return (
        contrib.T[:rays],
        o2.T[:rays],
        d2.T[:rays],
        thr2.T[:rays],
        alive2[0, :rays] > 0.5,
    )


def sphere_bounce_pallas(
    scene, origins, directions, throughput, alive, seed, bounce,
    *, total_bounces: int, lane=None, live_count=None,
):
    """One fused path-trace bounce for sphere-only scenes.

    The sphere megakernel's bounce_step as a single launch with path
    state streamed in/out — the sphere twin of ``mesh_bounce_pallas``,
    built for the wavefront driver (render/compaction.py): ``lane``
    carries each ray's ORIGINAL lane id (the RNG counter, so streams
    survive compaction) and ``live_count`` lets blocks entirely inside
    the compacted dead tail skip the bounce. Defaults reproduce the
    megakernel's full-width behavior (positional lanes, nothing
    skipped). Returns (radiance contribution [R, 3], new origins, new
    directions, new throughput, new alive).
    """
    n = origins.shape[0]
    if lane is None:
        lane = jnp.arange(n, dtype=jnp.int32)
    if live_count is None:
        live_count = jnp.int32(n)
    return _sphere_bounce(
        origins, directions, throughput, alive, lane, live_count, seed,
        bounce,
        scene.centers, scene.radii, scene.albedo, scene.emission,
        scene.sun_direction, scene.sun_color, scene.sky_horizon,
        scene.sky_zenith, scene.plane_albedo_a, scene.plane_albedo_b,
        total_bounces=total_bounces, interpret=_interpret(),
    )


# ---------------------------------------------------------------------------
# Stackless threaded-BVH packet traversal (SURVEY.md §7 hard part #4)
#
# One ray block walks the BVH with a single scalar node index (the threaded
# skip-link layout from render/mesh.py): the scalar unit steers the walk,
# the VPU tests the whole block against each node's AABB and — branchlessly
# — against the LEAF_SIZE-aligned triangle slot. Node metadata (skip /
# first / count and the 6 AABB scalars) lives in SMEM where dynamic scalar
# indexing is native; triangle data stays in VMEM and is fetched with a
# tile-aligned dynamic sublane slice (leaves occupy aligned 8-row slots by
# construction).

BVH_DONE_EPS = 1e-12
# Mesh-megakernel dispatch bound: use the fused whole-bounce-loop kernel
# when bvh_nodes x instances is at most this; deeper walks pay more for
# the in-kernel normal tracking than the fusion saves (see
# integrator.trace_paths for the on-chip measurements).
MESH_MEGAKERNEL_MAX_WALK = 1024


def mesh_megakernel_eligible(mesh) -> bool:
    """Single source of truth for the megakernel/per-bounce dispatch.

    Both trace_paths (which kernel) and render_tile (whether to flatten
    sample streams onto the ray axis) must agree — a drifted copy would
    flatten samples for a scene that then takes the per-bounce walk,
    hitting the packet-coherence cliff flattening is gated against.
    """
    return (
        mesh.bvh.skip.shape[0] * mesh.instances.translation.shape[0]
        <= MESH_MEGAKERNEL_MAX_WALK
    )


def _bvh_kernel_factory(n_nodes: int, leaf_size: int):
    def kernel(
        o_ref, d_ref, tinit_ref, v0_ref, e1_ref, e2_ref,
        bmin_ref, bmax_ref, skip_ref, first_ref, count_ref,
        t_ref, idx_ref,
    ):
        o = o_ref[:, :]  # [3, BR]
        d = d_ref[:, :]
        ox, oy, oz = o[0:1, :], o[1:2, :], o[2:3, :]
        dx, dy, dz = d[0:1, :], d[1:2, :], d[2:3, :]
        small = jnp.abs(d) < 1e-12
        inv = 1.0 / jnp.where(small, jnp.where(d < 0, -1e-12, 1e-12), d)
        invx, invy, invz = inv[0:1, :], inv[1:2, :], inv[2:3, :]
        block = o.shape[1]
        lanes = jax.lax.broadcasted_iota(jnp.int32, (leaf_size, block), 0)

        def cond(carry):
            node, _, _ = carry
            return node < n_nodes

        def body(carry):
            node, best_t, best_idx = carry
            # Packet AABB slab test against this node ([1, BR] per axis).
            lox = (bmin_ref[node, 0] - ox) * invx
            hix = (bmax_ref[node, 0] - ox) * invx
            loy = (bmin_ref[node, 1] - oy) * invy
            hiy = (bmax_ref[node, 1] - oy) * invy
            loz = (bmin_ref[node, 2] - oz) * invz
            hiz = (bmax_ref[node, 2] - oz) * invz
            tnear = jnp.maximum(
                jnp.maximum(jnp.minimum(lox, hix), jnp.minimum(loy, hiy)),
                jnp.minimum(loz, hiz),
            )
            tfar = jnp.minimum(
                jnp.minimum(jnp.maximum(lox, hix), jnp.maximum(loy, hiy)),
                jnp.maximum(loz, hiz),
            )
            packet_hit = (tfar >= jnp.maximum(tnear, 0.0)) & (tnear < best_t)
            hit_any = jnp.any(packet_hit)

            count = count_ref[node]
            is_leaf = count > 0
            start = first_ref[node]

            # Branchless leaf pass: Moeller-Trumbore for the whole aligned
            # slot, vectorized [leaf_size, BR]; masked to nothing on inner
            # nodes / packet misses.
            v0b = v0_ref[pl.dslice(start, leaf_size), :]
            e1b = e1_ref[pl.dslice(start, leaf_size), :]
            e2b = e2_ref[pl.dslice(start, leaf_size), :]
            v0x, v0y, v0z = v0b[:, 0:1], v0b[:, 1:2], v0b[:, 2:3]  # [L, 1]
            e1x, e1y, e1z = e1b[:, 0:1], e1b[:, 1:2], e1b[:, 2:3]
            e2x, e2y, e2z = e2b[:, 0:1], e2b[:, 1:2], e2b[:, 2:3]
            # pvec = d x e2 -> [L, BR]
            pvx = dy * e2z - dz * e2y
            pvy = dz * e2x - dx * e2z
            pvz = dx * e2y - dy * e2x
            det = e1x * pvx + e1y * pvy + e1z * pvz
            inv_det = 1.0 / jnp.where(jnp.abs(det) < BVH_DONE_EPS,
                                      BVH_DONE_EPS, det)
            tvx = ox - v0x
            tvy = oy - v0y
            tvz = oz - v0z
            u = (tvx * pvx + tvy * pvy + tvz * pvz) * inv_det
            # qvec = tvec x e1 -> [L, BR]
            qvx = tvy * e1z - tvz * e1y
            qvy = tvz * e1x - tvx * e1z
            qvz = tvx * e1y - tvy * e1x
            v = (dx * qvx + dy * qvy + dz * qvz) * inv_det
            tt = (e2x * qvx + e2y * qvy + e2z * qvz) * inv_det
            tri_hit = (
                (jnp.abs(det) > BVH_DONE_EPS)
                & (u >= 0.0)
                & (v >= 0.0)
                & (u + v <= 1.0)
                & (tt > EPS)
                & (lanes < count)
                & is_leaf
                & hit_any
            )
            t_cand = jnp.where(tri_hit, tt, INF)  # [L, BR]
            t_leaf = jnp.min(t_cand, axis=0, keepdims=True)  # [1, BR]
            local = jnp.min(
                jnp.where(t_cand == t_leaf, lanes, leaf_size),
                axis=0,
                keepdims=True,
            )
            closer = t_leaf < best_t
            best_t = jnp.where(closer, t_leaf, best_t)
            best_idx = jnp.where(
                closer, start + jnp.minimum(local, leaf_size - 1), best_idx
            )

            next_node = jnp.where(
                hit_any,
                jnp.where(is_leaf, skip_ref[node], node + 1),
                skip_ref[node],
            )
            return next_node, best_t, best_idx

        _, best_t, best_idx = jax.lax.while_loop(
            cond,
            body,
            (
                jnp.int32(0),
                tinit_ref[:, :],  # cull seed from earlier instances
                jnp.zeros((1, block), jnp.int32),
            ),
        )
        t_ref[:, :] = best_t
        idx_ref[:, :] = best_idx

    return kernel


def _pad_rays_to_miss(origins, directions, block: int = BVH_BLOCK_R):
    """Block-pad rays so pad lanes provably MISS the tree.

    A zero pad direction would turn the slab test degenerate (inv ~ 1e12
    hits every AABB) and — through the packet-wide any() — strip all BVH
    culling from the final block. A far-away origin with a perpendicular
    unit direction misses the root.
    """
    rays = origins.shape[0]
    padded_rays = -(-rays // block) * block
    ray_pad = padded_rays - rays
    o_t = jnp.pad(origins, ((0, ray_pad), (0, 0)), constant_values=1e7).T
    d_t = jnp.pad(directions, ((0, ray_pad), (0, 0))).T
    if ray_pad:
        d_t = d_t.at[1, rays:].set(1.0)
    return o_t, d_t, rays, padded_rays


@functools.partial(jax.jit, static_argnames=("interpret",))
def _bvh_nearest(
    origins, directions, init_t, v0, e1, e2, bounds_min, bounds_max, skip,
    first, count, *, interpret: bool,
):
    from tpu_render_cluster.render.mesh import LEAF_SIZE

    o_t, d_t, rays, padded_rays = _pad_rays_to_miss(origins, directions)
    t_init = jnp.pad(
        init_t[None, :], ((0, 0), (0, padded_rays - rays)),
        constant_values=INF,
    )

    n_nodes = skip.shape[0]
    grid = (padded_rays // BVH_BLOCK_R,)
    whole = lambda i: (0, 0)  # noqa: E731
    flat = lambda i: (0,)  # noqa: E731
    t, idx = pl.pallas_call(
        _bvh_kernel_factory(n_nodes, LEAF_SIZE),
        grid=grid,
        in_specs=[
            pl.BlockSpec((3, BVH_BLOCK_R), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((3, BVH_BLOCK_R), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, BVH_BLOCK_R), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec(v0.shape, whole, memory_space=pltpu.VMEM),
            pl.BlockSpec(e1.shape, whole, memory_space=pltpu.VMEM),
            pl.BlockSpec(e2.shape, whole, memory_space=pltpu.VMEM),
            pl.BlockSpec(bounds_min.shape, whole, memory_space=pltpu.SMEM),
            pl.BlockSpec(bounds_max.shape, whole, memory_space=pltpu.SMEM),
            pl.BlockSpec((n_nodes,), flat, memory_space=pltpu.SMEM),
            pl.BlockSpec((n_nodes,), flat, memory_space=pltpu.SMEM),
            pl.BlockSpec((n_nodes,), flat, memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, BVH_BLOCK_R), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, BVH_BLOCK_R), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, padded_rays), jnp.float32),
            jax.ShapeDtypeStruct((1, padded_rays), jnp.int32),
        ],
        interpret=interpret,
    )(o_t, d_t, t_init, v0, e1, e2, bounds_min, bounds_max, skip, first, count)
    return t[0, :rays], idx[0, :rays]


def intersect_bvh_pallas(bvh, origins, directions, init_t=None):
    """Pallas drop-in for ``mesh.intersect_bvh_packet`` (same results)."""
    if init_t is None:
        init_t = jnp.full((origins.shape[0],), INF, jnp.float32)
    return _bvh_nearest(
        origins, directions, init_t, bvh.v0, bvh.e1, bvh.e2,
        bvh.bounds_min, bvh.bounds_max, bvh.skip, bvh.first, bvh.count,
        interpret=_interpret(),
    )


def _bvh_anyhit_kernel_factory(n_nodes: int, leaf_size: int):
    def kernel(
        o_ref, d_ref, already_ref, v0_ref, e1_ref, e2_ref,
        bmin_ref, bmax_ref, skip_ref, first_ref, count_ref,
        occ_ref,
    ):
        o = o_ref[:, :]
        d = d_ref[:, :]
        ox, oy, oz = o[0:1, :], o[1:2, :], o[2:3, :]
        dx, dy, dz = d[0:1, :], d[1:2, :], d[2:3, :]
        small = jnp.abs(d) < 1e-12
        inv = 1.0 / jnp.where(small, jnp.where(d < 0, -1e-12, 1e-12), d)
        invx, invy, invz = inv[0:1, :], inv[1:2, :], inv[2:3, :]
        block = o.shape[1]
        lanes = jax.lax.broadcasted_iota(jnp.int32, (leaf_size, block), 0)

        def cond(carry):
            node, _ = carry
            return node < n_nodes

        def body(carry):
            node, occluded = carry
            lox = (bmin_ref[node, 0] - ox) * invx
            hix = (bmax_ref[node, 0] - ox) * invx
            loy = (bmin_ref[node, 1] - oy) * invy
            hiy = (bmax_ref[node, 1] - oy) * invy
            loz = (bmin_ref[node, 2] - oz) * invz
            hiz = (bmax_ref[node, 2] - oz) * invz
            tnear = jnp.maximum(
                jnp.maximum(jnp.minimum(lox, hix), jnp.minimum(loy, hiy)),
                jnp.minimum(loz, hiz),
            )
            tfar = jnp.minimum(
                jnp.minimum(jnp.maximum(lox, hix), jnp.maximum(loy, hiy)),
                jnp.maximum(loz, hiz),
            )
            packet_hit = (
                (tfar >= jnp.maximum(tnear, 0.0)) & (occluded <= 0.0)
            )
            hit_any = jnp.any(packet_hit)

            count = count_ref[node]
            is_leaf = count > 0
            start = first_ref[node]

            v0b = v0_ref[pl.dslice(start, leaf_size), :]
            e1b = e1_ref[pl.dslice(start, leaf_size), :]
            e2b = e2_ref[pl.dslice(start, leaf_size), :]
            v0x, v0y, v0z = v0b[:, 0:1], v0b[:, 1:2], v0b[:, 2:3]
            e1x, e1y, e1z = e1b[:, 0:1], e1b[:, 1:2], e1b[:, 2:3]
            e2x, e2y, e2z = e2b[:, 0:1], e2b[:, 1:2], e2b[:, 2:3]
            pvx = dy * e2z - dz * e2y
            pvy = dz * e2x - dx * e2z
            pvz = dx * e2y - dy * e2x
            det = e1x * pvx + e1y * pvy + e1z * pvz
            inv_det = 1.0 / jnp.where(
                jnp.abs(det) < BVH_DONE_EPS, BVH_DONE_EPS, det
            )
            tvx = ox - v0x
            tvy = oy - v0y
            tvz = oz - v0z
            u = (tvx * pvx + tvy * pvy + tvz * pvz) * inv_det
            qvx = tvy * e1z - tvz * e1y
            qvy = tvz * e1x - tvx * e1z
            qvz = tvx * e1y - tvy * e1x
            v = (dx * qvx + dy * qvy + dz * qvz) * inv_det
            tt = (e2x * qvx + e2y * qvy + e2z * qvz) * inv_det
            tri_hit = (
                (jnp.abs(det) > BVH_DONE_EPS)
                & (u >= 0.0)
                & (v >= 0.0)
                & (u + v <= 1.0)
                & (tt > EPS)
                & (lanes < count)
                & is_leaf
                & hit_any
            )
            occluded = jnp.maximum(
                occluded,
                jnp.max(jnp.where(tri_hit, 1.0, 0.0), axis=0, keepdims=True),
            )
            next_node = jnp.where(
                hit_any,
                jnp.where(is_leaf, skip_ref[node], node + 1),
                skip_ref[node],
            )
            return next_node, occluded

        _, occluded = jax.lax.while_loop(
            cond, body, (jnp.int32(0), already_ref[:, :])
        )
        occ_ref[:, :] = occluded

    return kernel


@functools.partial(jax.jit, static_argnames=("interpret",))
def _bvh_anyhit(
    origins, directions, already, v0, e1, e2, bounds_min, bounds_max, skip,
    first, count, *, interpret: bool,
):
    from tpu_render_cluster.render.mesh import LEAF_SIZE

    o_t, d_t, rays, padded_rays = _pad_rays_to_miss(origins, directions)
    # Pad lanes start "occluded" so they never extend the walk.
    already_f = jnp.pad(
        already.astype(jnp.float32)[None, :],
        ((0, 0), (0, padded_rays - rays)),
        constant_values=1.0,
    )

    n_nodes = skip.shape[0]
    grid = (padded_rays // BVH_BLOCK_R,)
    whole = lambda i: (0, 0)  # noqa: E731
    flat = lambda i: (0,)  # noqa: E731
    occ = pl.pallas_call(
        _bvh_anyhit_kernel_factory(n_nodes, LEAF_SIZE),
        grid=grid,
        in_specs=[
            pl.BlockSpec((3, BVH_BLOCK_R), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((3, BVH_BLOCK_R), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, BVH_BLOCK_R), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec(v0.shape, whole, memory_space=pltpu.VMEM),
            pl.BlockSpec(e1.shape, whole, memory_space=pltpu.VMEM),
            pl.BlockSpec(e2.shape, whole, memory_space=pltpu.VMEM),
            pl.BlockSpec(bounds_min.shape, whole, memory_space=pltpu.SMEM),
            pl.BlockSpec(bounds_max.shape, whole, memory_space=pltpu.SMEM),
            pl.BlockSpec((n_nodes,), flat, memory_space=pltpu.SMEM),
            pl.BlockSpec((n_nodes,), flat, memory_space=pltpu.SMEM),
            pl.BlockSpec((n_nodes,), flat, memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, BVH_BLOCK_R), lambda i: (0, i), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((1, padded_rays), jnp.float32),
        interpret=interpret,
    )(o_t, d_t, already_f, v0, e1, e2, bounds_min, bounds_max, skip, first, count)
    return occ[0, :rays] > 0.0


def occluded_bvh_pallas(bvh, origins, directions, already):
    """Pallas drop-in for ``mesh.occluded_bvh_packet`` (same results)."""
    return _bvh_anyhit(
        origins, directions, already, bvh.v0, bvh.e1, bvh.e2,
        bvh.bounds_min, bvh.bounds_max, bvh.skip, bvh.first, bvh.count,
        interpret=_interpret(),
    )


# ---------------------------------------------------------------------------
# Instanced BVH traversal: ALL instances in one kernel launch.
#
# The scan-over-instances alternative executes the single-instance kernel K
# times per pass; here the grid is (ray_blocks, K) with k minormost, so the
# output block for a ray block stays VMEM-resident while every instance
# walks it (initialize at k == 0, min-accumulate after). Instance
# transforms (9 rotation + 3 translation + 1 inv-scale scalars) live in
# SMEM and are applied to the ray block in-kernel — no [K*R] ray
# materialization in HBM, one launch per pass instead of K.


def _bvh_instanced_kernel_factory(
    n_nodes: int, leaf_size: int, k_count: int, anyhit: bool
):
    def kernel(o_ref, d_ref, *rest):
        if anyhit:
            (inst_ref, v0_ref, e1_ref, e2_ref, bmin_ref, bmax_ref,
             skip_ref, first_ref, count_ref, *out_refs) = rest
        else:
            # Nearest variant carries a seed-t input (the caller's already
            # known closest hit — sphere/plane t from the same bounce, so
            # walks that cannot beat it are culled before they start) and a
            # per-block CANDIDATE instance (the broadphase's nearest-entry
            # AABB for the block's first lane; the integrator sorts rays by
            # candidate, so one id represents the block).
            (tinit_ref, cand_ref, inst_ref, v0_ref, e1_ref, e2_ref,
             bmin_ref, bmax_ref, skip_ref, first_ref, count_ref,
             *out_refs) = rest

        # One grid step per RAY BLOCK; instances run in an in-kernel fori
        # loop. (An earlier revision put instances on a second grid axis —
        # 48x more grid steps, each paying block-copy + bookkeeping
        # overhead and round-tripping best-t through the output refs.)
        wo = o_ref[:, :]
        wd = d_ref[:, :]
        block = wo.shape[1]

        def winv(v):
            small = jnp.abs(v) < 1e-12
            return 1.0 / jnp.where(small, jnp.where(v < 0, -1e-12, 1e-12), v)

        wox, woy, woz = wo[0:1, :], wo[1:2, :], wo[2:3, :]
        wdx, wdy, wdz = wd[0:1, :], wd[1:2, :], wd[2:3, :]
        wix, wiy, wiz = winv(wdx), winv(wdy), winv(wdz)
        lanes = jax.lax.broadcasted_iota(jnp.int32, (leaf_size, block), 0)

        def per_instance(k, carry):
            # World -> object from SMEM scalars (x' = R^T (x - t) / s; the
            # direction scales by 1/s too so t stays in world units).
            r00, r01, r02 = inst_ref[k, 0], inst_ref[k, 1], inst_ref[k, 2]
            r10, r11, r12 = inst_ref[k, 3], inst_ref[k, 4], inst_ref[k, 5]
            r20, r21, r22 = inst_ref[k, 6], inst_ref[k, 7], inst_ref[k, 8]
            tx, ty, tz = inst_ref[k, 9], inst_ref[k, 10], inst_ref[k, 11]
            inv_s = inst_ref[k, 12]

            if anyhit:
                # Lanes occluded by earlier instances stop driving the cull.
                cull_limit = jnp.where(carry > 0.0, -INF, INF)
            else:
                # Per-lane best-so-far (seeded with the caller's
                # sphere/plane t): an instance whose AABB entry lies beyond
                # every lane's current best cannot improve anything.
                cull_limit = carry[0]

            # Top-level cull: slab-test the ray block against this
            # instance's WORLD AABB with the untransformed rays; skip the
            # whole walk when nothing in the block can touch the instance.
            wlox = (inst_ref[k, 13] - wox) * wix
            whix = (inst_ref[k, 16] - wox) * wix
            wloy = (inst_ref[k, 14] - woy) * wiy
            whiy = (inst_ref[k, 17] - woy) * wiy
            wloz = (inst_ref[k, 15] - woz) * wiz
            whiz = (inst_ref[k, 18] - woz) * wiz
            wnear = jnp.maximum(
                jnp.maximum(jnp.minimum(wlox, whix), jnp.minimum(wloy, whiy)),
                jnp.minimum(wloz, whiz),
            )
            wfar = jnp.minimum(
                jnp.minimum(jnp.maximum(wlox, whix), jnp.maximum(wloy, whiy)),
                jnp.maximum(wloz, whiz),
            )
            touch = jnp.any(
                (wfar >= jnp.maximum(wnear, 0.0)) & (wnear < cull_limit)
            )

            def run_walk():
                sx, sy, sz = wox - tx, woy - ty, woz - tz
                # Column j of R^T is row j of R: o'_i = sum_j s_j * R[j][i].
                ox = (sx * r00 + sy * r10 + sz * r20) * inv_s
                oy = (sx * r01 + sy * r11 + sz * r21) * inv_s
                oz = (sx * r02 + sy * r12 + sz * r22) * inv_s
                dx = (wdx * r00 + wdy * r10 + wdz * r20) * inv_s
                dy = (wdx * r01 + wdy * r11 + wdz * r21) * inv_s
                dz = (wdx * r02 + wdy * r12 + wdz * r22) * inv_s
                invx, invy, invz = winv(dx), winv(dy), winv(dz)

                def cond(walk):
                    # (An all-lanes-occluded early exit for the anyhit walk
                    # was measured slower: the per-iteration cross-lane
                    # reduction costs more than the iterations it saves.)
                    return walk[0] < n_nodes

                def body(walk):
                    if anyhit:
                        node, occluded = walk
                        best_t = jnp.where(occluded > 0.0, -INF, INF)
                    else:
                        node, best_t, best_tri, best_inst = walk
                    lox = (bmin_ref[node, 0] - ox) * invx
                    hix = (bmax_ref[node, 0] - ox) * invx
                    loy = (bmin_ref[node, 1] - oy) * invy
                    hiy = (bmax_ref[node, 1] - oy) * invy
                    loz = (bmin_ref[node, 2] - oz) * invz
                    hiz = (bmax_ref[node, 2] - oz) * invz
                    tnear = jnp.maximum(
                        jnp.maximum(
                            jnp.minimum(lox, hix), jnp.minimum(loy, hiy)
                        ),
                        jnp.minimum(loz, hiz),
                    )
                    tfar = jnp.minimum(
                        jnp.minimum(
                            jnp.maximum(lox, hix), jnp.maximum(loy, hiy)
                        ),
                        jnp.maximum(loz, hiz),
                    )
                    packet_hit = (
                        tfar >= jnp.maximum(tnear, 0.0)
                    ) & (tnear < best_t)
                    hit_any = jnp.any(packet_hit)

                    count = count_ref[node]
                    is_leaf = count > 0
                    start = first_ref[node]

                    def leaf_test():
                        # The [leaf_size, block] Möller-Trumbore test — the
                        # walk's dominant vector work. ``is_leaf & hit_any``
                        # is a SCALAR (the whole block walks the same node),
                        # so this runs under a real scalar-unit branch:
                        # internal nodes and culled subtrees skip it
                        # entirely instead of computing-and-masking (~2x on
                        # deep walks, where half the visited nodes are
                        # internal).
                        v0b = v0_ref[pl.dslice(start, leaf_size), :]
                        e1b = e1_ref[pl.dslice(start, leaf_size), :]
                        e2b = e2_ref[pl.dslice(start, leaf_size), :]
                        v0x, v0y, v0z = v0b[:, 0:1], v0b[:, 1:2], v0b[:, 2:3]
                        e1x, e1y, e1z = e1b[:, 0:1], e1b[:, 1:2], e1b[:, 2:3]
                        e2x, e2y, e2z = e2b[:, 0:1], e2b[:, 1:2], e2b[:, 2:3]
                        pvx = dy * e2z - dz * e2y
                        pvy = dz * e2x - dx * e2z
                        pvz = dx * e2y - dy * e2x
                        det = e1x * pvx + e1y * pvy + e1z * pvz
                        inv_det = 1.0 / jnp.where(
                            jnp.abs(det) < BVH_DONE_EPS, BVH_DONE_EPS, det
                        )
                        tvx = ox - v0x
                        tvy = oy - v0y
                        tvz = oz - v0z
                        u = (tvx * pvx + tvy * pvy + tvz * pvz) * inv_det
                        qvx = tvy * e1z - tvz * e1y
                        qvy = tvz * e1x - tvx * e1z
                        qvz = tvx * e1y - tvy * e1x
                        v = (dx * qvx + dy * qvy + dz * qvz) * inv_det
                        tt = (e2x * qvx + e2y * qvy + e2z * qvz) * inv_det
                        tri_hit = (
                            (jnp.abs(det) > BVH_DONE_EPS)
                            & (u >= 0.0)
                            & (v >= 0.0)
                            & (u + v <= 1.0)
                            & (tt > EPS)
                            & (lanes < count)
                        )
                        if anyhit:
                            return (
                                jnp.max(
                                    jnp.where(tri_hit, 1.0, 0.0),
                                    axis=0,
                                    keepdims=True,
                                ),
                                jnp.zeros((1, block), jnp.int32),
                            )
                        t_cand = jnp.where(tri_hit, tt, INF)
                        t_leaf = jnp.min(t_cand, axis=0, keepdims=True)
                        local = jnp.min(
                            jnp.where(t_cand == t_leaf, lanes, leaf_size),
                            axis=0,
                            keepdims=True,
                        )
                        return t_leaf, local

                    def leaf_skip():
                        if anyhit:
                            return (
                                jnp.zeros((1, block), jnp.float32),
                                jnp.zeros((1, block), jnp.int32),
                            )
                        return (
                            jnp.full((1, block), INF, jnp.float32),
                            jnp.zeros((1, block), jnp.int32),
                        )

                    leaf_a, leaf_b = jax.lax.cond(
                        is_leaf & hit_any, leaf_test, leaf_skip
                    )
                    next_node = jnp.where(
                        hit_any,
                        jnp.where(is_leaf, skip_ref[node], node + 1),
                        skip_ref[node],
                    )
                    if anyhit:
                        occluded = jnp.maximum(occluded, leaf_a)
                        return next_node, occluded
                    t_leaf, local = leaf_a, leaf_b
                    closer = t_leaf < best_t
                    best_t = jnp.where(closer, t_leaf, best_t)
                    best_tri = jnp.where(
                        closer,
                        start + jnp.minimum(local, leaf_size - 1),
                        best_tri,
                    )
                    best_inst = jnp.where(closer, k, best_inst)
                    return next_node, best_t, best_tri, best_inst

                if anyhit:
                    _, occluded = jax.lax.while_loop(
                        cond, body, (jnp.int32(0), carry)
                    )
                    return occluded
                _, best_t, best_tri, best_inst = jax.lax.while_loop(
                    cond, body, (jnp.int32(0), *carry)
                )
                return (best_t, best_tri, best_inst)

            return jax.lax.cond(touch, run_walk, lambda: carry)

        if anyhit:
            occ_ref, = out_refs
            # Already-occluded rays are folded in by the wrapper (replaced
            # with guaranteed-miss rays), so the walk starts all-clear
            # (_bvh_anyhit_instanced).
            occluded = jax.lax.fori_loop(
                0, k_count, per_instance, jnp.zeros((1, block), jnp.float32)
            )
            occ_ref[:, :] = occluded
        else:
            t_ref, tri_ref, inst_out_ref = out_refs
            init = (
                tinit_ref[:, :],
                jnp.zeros((1, block), jnp.int32),
                jnp.zeros((1, block), jnp.int32),
            )
            # Walk the block's candidate instance FIRST: most lanes hit it,
            # so the sweep below starts with tight per-lane best-t and the
            # top-level cull rejects most of the remaining instances.
            cand = cand_ref[0, pl.program_id(0)]
            init = jax.lax.cond(
                cand < k_count,
                lambda: per_instance(cand, init),
                lambda: init,
            )
            best_t, best_tri, best_inst = jax.lax.fori_loop(
                0,
                k_count,
                lambda k, c: jax.lax.cond(
                    k == cand, lambda: c, lambda: per_instance(k, c)
                ),
                init,
            )
            t_ref[:, :] = best_t
            tri_ref[:, :] = best_tri
            inst_out_ref[:, :] = best_inst

    return kernel


def _instance_table(rotation, translation, scale, bounds_min, bounds_max,
                    albedo=None):
    """[K, 22] SMEM table: rotation row-major (0..8), translation (9..11),
    1/scale (12), the instance's WORLD-space AABB (13..18) — the top-level
    cull the kernel applies before paying for the object-space walk — and
    the instance albedo (19..21; zeros when the caller doesn't need it).

    World AABB of a transformed box: center_w = s R c_o + t,
    half_w = s |R| h_o (elementwise absolute rotation).
    """
    k = rotation.shape[0]
    center_obj = 0.5 * (bounds_min[0] + bounds_max[0])  # root node
    half_obj = 0.5 * (bounds_max[0] - bounds_min[0])
    center_w = (
        scale[:, None] * jnp.einsum(
            "kij,j->ki", rotation, center_obj, precision="highest"
        )
        + translation
    )
    half_w = scale[:, None] * jnp.einsum(
        "kij,j->ki", jnp.abs(rotation), half_obj, precision="highest"
    )
    if albedo is None:
        albedo = jnp.zeros((k, 3), jnp.float32)
    return jnp.concatenate(
        [
            rotation.reshape(k, 9),
            translation,
            (1.0 / scale)[:, None],
            center_w - half_w,
            center_w + half_w,
            albedo,
        ],
        axis=1,
    )


def _instanced_specs(inst_table, v0, e1, e2, bounds_min, bounds_max, n_nodes):
    whole = lambda i: (0, 0)  # noqa: E731
    flat = lambda i: (0,)  # noqa: E731
    return [
        pl.BlockSpec((3, BVH_BLOCK_R), lambda i: (0, i), memory_space=pltpu.VMEM),
        pl.BlockSpec((3, BVH_BLOCK_R), lambda i: (0, i), memory_space=pltpu.VMEM),
        pl.BlockSpec(inst_table.shape, whole, memory_space=pltpu.SMEM),
        pl.BlockSpec(v0.shape, whole, memory_space=pltpu.VMEM),
        pl.BlockSpec(e1.shape, whole, memory_space=pltpu.VMEM),
        pl.BlockSpec(e2.shape, whole, memory_space=pltpu.VMEM),
        pl.BlockSpec(bounds_min.shape, whole, memory_space=pltpu.SMEM),
        pl.BlockSpec(bounds_max.shape, whole, memory_space=pltpu.SMEM),
        pl.BlockSpec((n_nodes,), flat, memory_space=pltpu.SMEM),
        pl.BlockSpec((n_nodes,), flat, memory_space=pltpu.SMEM),
        pl.BlockSpec((n_nodes,), flat, memory_space=pltpu.SMEM),
    ]


def instance_entry_candidates(origins, directions, lo_w, hi_w):
    """Per-ray broadphase: nearest-entry overlapped instance world AABB.

    One fused [R, K] slab-test pass; returns [R] int32 with K (= the
    instance count) for rays overlapping nothing. Shared by the
    integrator's coherence sort key and the nearest wrapper's per-block
    candidates — a single copy so an epsilon change can't desynchronize
    the sort from the kernel's walk order.
    """
    small = jnp.abs(directions) < 1e-12
    inv = 1.0 / jnp.where(
        small, jnp.where(directions < 0, -1e-12, 1e-12), directions
    )
    t0 = (lo_w[None, :, :] - origins[:, None, :]) * inv[:, None, :]
    t1 = (hi_w[None, :, :] - origins[:, None, :]) * inv[:, None, :]
    near = jnp.max(jnp.minimum(t0, t1), axis=2)  # [R, K]
    far = jnp.min(jnp.maximum(t0, t1), axis=2)
    overlap = far >= jnp.maximum(near, 0.0)
    entry = jnp.where(overlap, jnp.maximum(near, 0.0), jnp.float32(INF))
    return jnp.where(
        jnp.any(overlap, axis=1),
        jnp.argmin(entry, axis=1),
        lo_w.shape[0],
    ).astype(jnp.int32)


def _block_candidates(origins, directions, lo_w, hi_w):
    """Nearest-entry overlapped instance AABB per ray block, from the
    block's FIRST lane (the integrator sorts rays by candidate, so one
    lane represents the block). K = no overlap. [1, n_blocks] int32.
    """
    rays = origins.shape[0]
    n_blocks = -(-rays // BVH_BLOCK_R)
    stride = jnp.arange(n_blocks) * BVH_BLOCK_R
    first_lane = jnp.minimum(stride, rays - 1)
    return instance_entry_candidates(
        origins[first_lane], directions[first_lane], lo_w, hi_w
    )[None, :]


def _bvh_nearest_instanced(
    origins, directions, t_init, block_candidate, rotation, translation,
    scale, v0, e1, e2, bounds_min, bounds_max, skip, first, count,
    *, interpret: bool,
):
    from tpu_render_cluster.render.mesh import LEAF_SIZE

    o_t, d_t, rays, padded_rays = _pad_rays_to_miss(origins, directions)
    t_init_t = jnp.full((1, padded_rays), INF, jnp.float32)
    t_init_t = t_init_t.at[0, :rays].set(t_init)
    inst_table = _instance_table(
        rotation, translation, scale, bounds_min, bounds_max
    )
    n_nodes = skip.shape[0]
    k_count = rotation.shape[0]
    n_blocks = padded_rays // BVH_BLOCK_R
    grid = (n_blocks,)
    out_block = pl.BlockSpec(
        (1, BVH_BLOCK_R), lambda i: (0, i), memory_space=pltpu.VMEM
    )
    in_specs = _instanced_specs(
        inst_table, v0, e1, e2, bounds_min, bounds_max, n_nodes
    )
    # Seed-t rides a third ray-indexed block after origins/directions; the
    # per-block candidate follows as a one-scalar SMEM block.
    in_specs.insert(
        2,
        pl.BlockSpec(
            (1, BVH_BLOCK_R), lambda i: (0, i), memory_space=pltpu.VMEM
        ),
    )
    # The whole per-block candidate vector rides in SMEM as a [1, n] row
    # (rank-2 sidesteps Pallas TPU's rank-1 block tiling constraint AND
    # vmap's batching of rank-1 SMEM blocks); the kernel indexes it by
    # program_id.
    in_specs.insert(
        3,
        pl.BlockSpec(
            (1, n_blocks), lambda i: (0, 0), memory_space=pltpu.SMEM
        ),
    )
    t, tri, inst = pl.pallas_call(
        _bvh_instanced_kernel_factory(n_nodes, LEAF_SIZE, k_count, anyhit=False),
        grid=grid,
        in_specs=in_specs,
        out_specs=[out_block, out_block, out_block],
        out_shape=[
            jax.ShapeDtypeStruct((1, padded_rays), jnp.float32),
            jax.ShapeDtypeStruct((1, padded_rays), jnp.int32),
            jax.ShapeDtypeStruct((1, padded_rays), jnp.int32),
        ],
        interpret=interpret,
    )(o_t, d_t, t_init_t, block_candidate, inst_table, v0, e1, e2,
      bounds_min, bounds_max, skip, first, count)
    return t[0, :rays], tri[0, :rays], inst[0, :rays]


def _bvh_anyhit_instanced(
    origins, directions, already, rotation, translation, scale,
    v0, e1, e2, bounds_min, bounds_max, skip, first, count,
    *, interpret: bool,
):
    from tpu_render_cluster.render.mesh import LEAF_SIZE

    # Fold the `already` mask into the rays: an already-occluded ray is
    # replaced by a guaranteed-miss ray (the kernel initializes occluded=0
    # at k == 0, so a pre-set mask cannot ride the output buffer), and the
    # mask is OR-ed back on afterwards.
    masked_origins = jnp.where(already[:, None], 1e7, origins)
    masked_directions = jnp.where(
        already[:, None],
        jnp.array([0.0, 1.0, 0.0], jnp.float32)[None, :],
        directions,
    )
    o_t, d_t, rays, padded_rays = _pad_rays_to_miss(
        masked_origins, masked_directions
    )
    inst_table = _instance_table(
        rotation, translation, scale, bounds_min, bounds_max
    )
    n_nodes = skip.shape[0]
    k_count = rotation.shape[0]
    grid = (padded_rays // BVH_BLOCK_R,)
    occ = pl.pallas_call(
        _bvh_instanced_kernel_factory(n_nodes, LEAF_SIZE, k_count, anyhit=True),
        grid=grid,
        in_specs=_instanced_specs(
            inst_table, v0, e1, e2, bounds_min, bounds_max, n_nodes
        ),
        out_specs=pl.BlockSpec(
            (1, BVH_BLOCK_R), lambda i: (0, i), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((1, padded_rays), jnp.float32),
        interpret=interpret,
    )(o_t, d_t, inst_table, v0, e1, e2, bounds_min, bounds_max, skip, first,
      count)
    return (occ[0, :rays] > 0.0) | already


# ---------------------------------------------------------------------------
# Mesh megakernel: the WHOLE bounce loop for mesh scenes in one kernel.
#
# The sphere megakernel (_trace_kernel_factory) keeps path state
# VMEM-resident across bounces; mesh scenes previously fell back to the
# per-bounce XLA scan with 2 BVH kernel launches + HBM round trips of every
# [R, 3] state buffer per bounce. This kernel subsumes both: per bounce it
# runs the sphere/plane nearest hit, an IN-KERNEL instanced threaded-BVH
# walk (fori over instances, while over nodes — same two-level TLAS/BLAS
# shape as the standalone instanced kernels), sun NEE with both sphere and
# mesh any-hit occlusion, and the counter-based PCG resample. Per-lane
# mesh normals/albedo are tracked through winner one-hots during the leaf
# pass (TPU Pallas has no per-lane vector gather); shadow rays toward the
# uniform sun direction transform per instance as SCALARS.
#
# The sphere/plane/sky/NEE/resample physics is intentionally the same
# code shape as _trace_kernel_factory; both kernels are pinned to the ONE
# XLA reference implementation by deterministic single-bounce equivalence
# tests (test_pallas_kernels.py, test_mesh_megakernel.py), so a physics
# edit applied to only one kernel fails its test rather than silently
# diverging.


def _mesh_trace_kernel_factory(
    max_bounces: int, n_padded: int, n_nodes: int, leaf_size: int,
    k_count: int, state_io: bool = False, pool_io: bool = False,
    k_per_frame: int = 0, use_tlas: bool = False, tlas_nodes: int = 0,
    tlas_per_frame: int = 0, quant: int = 0, ordered: bool = False,
    tlas_ordered: bool = False,
):
    """Mesh path-trace kernel. Three shapes share one bounce_step:

    - state_io=False: the whole-bounce-loop MEGAKERNEL (state VMEM-resident
      across all bounces, radiance out) — shallow-walk scenes.
    - state_io=True: ONE bounce per launch with path state streamed in/out
      (o, d, throughput, alive + this bounce's radiance contribution), so
      the integrator can re-sort rays for packet coherence between bounces
      while everything else (sphere+plane+mesh nearest, NEE with both
      any-hits, shading, in-kernel PCG resample) stays fused — deep-walk
      scenes. ``max_bounces`` still names the TOTAL bounce count so the
      per-(ray, bounce) RNG counters match the megakernel's stream layout.
    - pool_io=True: the device-resident ray-pool shape
      (render/raypool.py): per-lane seed/bounce rows (lanes from
      different frames at different depths share one launch; the
      carried (frame seed, original lane, bounce) triple reproduces the
      masked loop's RNG streams), a multi-frame sphere STACK with a
      per-sphere frame-id column, and a 23rd instance-table column
      carrying each instance's frame id — lanes whose frame id doesn't
      match an instance are packet-culled from its walk (their slab
      limit is -INF) and can neither update best-t nor be shadowed by
      it, so every lane sees exactly its own frame's geometry.
    """
    contract_first = (((0,), (0,)), ((), ()))

    def kernel(*refs):
        # Fixed-prefix unpacking, then the BLAS node block (fp32: 5 SMEM
        # refs; quantized: packed bq/meta words + grid scalars), the
        # optional TLAS node block (same two formats), the key-bounds
        # scalars + fused sort-key output (streamed-state TLAS kernels
        # only — flat kernels keep today's signature so the A/B baseline
        # is untouched), and finally the state outputs.
        refs = list(refs)

        def take(n):
            out, refs[:n] = tuple(refs[:n]), []
            return out

        if pool_io:
            (live_ref, o_ref, d_ref, thr_ref, alive_ref, lane_ref,
             seed_row_ref, bounce_row_ref, fid_row_ref,
             fid_lo_ref, fid_hi_ref,
             c_ref, r2_ref, csq_ref, rad_ref, albedo_ref, emission_ref,
             dcsun_ref, sfid_ref, params_ref, sunsm_ref, inst_ref,
             v0_ref, e1_ref, e2_ref, nrm_ref) = take(26)
        elif state_io:
            (seed_ref, bounce_ref, live_ref, o_ref, d_ref, thr_ref,
             alive_ref, lane_ref,
             c_ref, r2_ref, csq_ref, rad_ref, albedo_ref, emission_ref,
             dcsun_ref, params_ref, sunsm_ref, inst_ref, v0_ref, e1_ref,
             e2_ref, nrm_ref) = take(22)
        else:
            (seed_ref, o_ref, d_ref, c_ref, r2_ref, csq_ref, rad_ref,
             albedo_ref, emission_ref, dcsun_ref, params_ref, sunsm_ref,
             inst_ref, v0_ref, e1_ref, e2_ref, nrm_ref) = take(17)
        if quant:
            (bq_ref, bmeta_ref, bgrid_ref) = take(3)
        else:
            (bmin_ref, bmax_ref, skip_ref, first_ref, count_ref) = take(5)
        if use_tlas:
            if quant:
                (tbq_ref, tmeta_ref, tgrid_ref) = take(3)
            else:
                (tbmin_ref, tbmax_ref, tskip_ref, tfirst_ref,
                 tcount_ref) = take(5)
        if (state_io or pool_io) and use_tlas:
            (keysm_ref,) = take(1)
            (out_ref, o_out_ref, d_out_ref, thr_out_ref, alive_out_ref,
             key_out_ref) = refs
        elif state_io or pool_io:
            (out_ref, o_out_ref, d_out_ref, thr_out_ref,
             alive_out_ref) = refs
        else:
            (out_ref,) = refs

        # -- node-table readers -----------------------------------------
        # ONE reconstruction per format, shared by every walk below. The
        # quantized form reads 1-2 int32 words per node and reconstructs
        # slabs as origin + q * cell in f32 — conservatively OUTSIDE the
        # fp32 box by construction (mesh.quantize_node_tables), so culls
        # stay exact-superset and results bit-identical. Meta packs
        # skip | first/unit << 16 | count << 27 into one scalar read.

        def _read_packed_bounds(bqr, gridr, node):
            if quant == 1:
                w0, w1, w2 = bqr[node, 0], bqr[node, 1], bqr[node, 2]
                qlx, qhx = w0 & 0xFFFF, (w0 >> 16) & 0xFFFF
                qly, qhy = w1 & 0xFFFF, (w1 >> 16) & 0xFFFF
                qlz, qhz = w2 & 0xFFFF, (w2 >> 16) & 0xFFFF
            else:
                w0, w1 = bqr[node, 0], bqr[node, 1]
                qlx, qly = w0 & 0xFF, (w0 >> 8) & 0xFF
                qlz, qhx = (w0 >> 16) & 0xFF, (w0 >> 24) & 0xFF
                qhy, qhz = w1 & 0xFF, (w1 >> 8) & 0xFF
            gx, gy, gz = gridr[0], gridr[1], gridr[2]
            cx, cy, cz = gridr[3], gridr[4], gridr[5]
            return (
                gx + qlx.astype(jnp.float32) * cx,
                gy + qly.astype(jnp.float32) * cy,
                gz + qlz.astype(jnp.float32) * cz,
                gx + qhx.astype(jnp.float32) * cx,
                gy + qhy.astype(jnp.float32) * cy,
                gz + qhz.astype(jnp.float32) * cz,
            )

        def _read_meta(metar, node, unit):
            meta = metar[node]
            return (
                meta & 0xFFFF,
                ((meta >> 16) & 0x7FF) * unit,
                (meta >> 27) & 0x1F,
            )

        def blas_node(node):
            """(6 slab scalars, skip, leaf start, leaf count)."""
            if quant:
                return (
                    _read_packed_bounds(bq_ref, bgrid_ref, node),
                    *_read_meta(bmeta_ref, node, leaf_size),
                )
            return (
                (bmin_ref[node, 0], bmin_ref[node, 1], bmin_ref[node, 2],
                 bmax_ref[node, 0], bmax_ref[node, 1], bmax_ref[node, 2]),
                skip_ref[node], first_ref[node], count_ref[node],
            )

        if use_tlas:
            def tlas_node(node):
                if quant:
                    return (
                        _read_packed_bounds(tbq_ref, tgrid_ref, node),
                        *_read_meta(tmeta_ref, node, 1),
                    )
                return (
                    (tbmin_ref[node, 0], tbmin_ref[node, 1],
                     tbmin_ref[node, 2],
                     tbmax_ref[node, 0], tbmax_ref[node, 1],
                     tbmax_ref[node, 2]),
                    tskip_ref[node], tfirst_ref[node], tcount_ref[node],
                )
        if use_tlas:
            # THE threaded skip-link walk over TLAS node slabs, shared
            # by the nearest, any-hit, and key-epilogue entry walks
            # (same rule as the BLAS walk_step: a traversal/epsilon fix
            # lands once). Call sites differ only in the ray components,
            # the per-lane ``limit_of(carry)`` driving the packet test,
            # and the ``leaf_body`` fori callback over a leaf's slot
            # range; ``carry`` is a tuple.
            def tlas_walk(
                node0, node_end, tbase, ox, oy, oz, ix, iy, iz,
                limit_of, leaf_body, carry,
            ):
                def cond(walk):
                    return walk[0] < node_end

                def body(walk):
                    node = walk[0]
                    carry = tuple(walk[1:])
                    limit = limit_of(carry)
                    (nlx, nly, nlz, nhx, nhy, nhz), nskip, start, cnt = (
                        tlas_node(tbase + node)
                    )
                    lox = (nlx - ox) * ix
                    hix = (nhx - ox) * ix
                    loy = (nly - oy) * iy
                    hiy = (nhy - oy) * iy
                    loz = (nlz - oz) * iz
                    hiz = (nhz - oz) * iz
                    tnear = jnp.maximum(
                        jnp.maximum(
                            jnp.minimum(lox, hix), jnp.minimum(loy, hiy)
                        ),
                        jnp.minimum(loz, hiz),
                    )
                    tfar = jnp.minimum(
                        jnp.minimum(
                            jnp.maximum(lox, hix), jnp.maximum(loy, hiy)
                        ),
                        jnp.maximum(loz, hiz),
                    )
                    packet_hit = (
                        tfar >= jnp.maximum(tnear, 0.0)
                    ) & (tnear < limit)
                    hit_any = jnp.any(packet_hit)
                    is_leaf = cnt > 0
                    next_node = jnp.where(
                        hit_any,
                        jnp.where(is_leaf, nskip, node + 1),
                        nskip,
                    )
                    carry = jax.lax.cond(
                        is_leaf & hit_any,
                        lambda: jax.lax.fori_loop(
                            start, start + cnt, leaf_body, carry
                        ),
                        lambda: carry,
                    )
                    return (next_node, *carry)

                return tuple(
                    jax.lax.while_loop(cond, body, (node0, *carry))
                )[1:]

        o = o_ref[:, :]  # [3, BR]
        d = d_ref[:, :]
        c = c_ref[:, :]
        r2 = r2_ref[:, :]
        csq = csq_ref[:, :]
        radius = rad_ref[:, :]
        albedo_t = albedo_ref[:, :]
        emission_t = emission_ref[:, :]
        dc_sun = dcsun_ref[:, :]
        params = params_ref[:, :]
        sun = params[0:1, :].T
        sun_color = params[1:2, :].T
        sky_horizon = params[2:3, :].T
        sky_zenith = params[3:4, :].T
        plane_a = params[4:5, :].T
        plane_b = params[5:6, :].T

        block = o.shape[1]
        if pool_io:
            # Per-lane frame seed + frame-id row (see the factory doc).
            seed = seed_row_ref[:, :].astype(jnp.uint32)  # [1, BR]
            ray_index = lane_ref[:, :].astype(jnp.uint32)
            fid_row = fid_row_ref[:, :]  # [1, BR] float32 frame ids
            fid_match = sfid_ref[:, :] == fid_row  # [N, BR]
            # This block's frame-id RANGE (true scalars, SMEM): the
            # instance table is FID-MAJOR with exactly k_per_frame rows
            # per frame, so the in-kernel sweeps iterate only the
            # contiguous [fid_lo * K, (fid_hi + 1) * K) slice — the
            # fid-major pool sort makes blocks frame-pure, and the
            # stacked multi-frame sweep then costs exactly one frame's
            # instances. Conservative by construction (the range covers
            # ALL lanes, stale dead ones included): a too-wide window
            # only walks instances whose matching lanes are dead, and
            # their -INF limits exit those walks at the first node.
            k_sweep_lo = fid_lo_ref[0, 0] * k_per_frame
            k_sweep_hi = jnp.minimum(
                (fid_hi_ref[0, 0] + 1) * k_per_frame, k_count
            )
        else:
            seed = seed_ref[0, 0].astype(jnp.uint32)
            fid_row = None
            fid_match = None
            if state_io:
                # RNG counters follow the ORIGINAL lane id the integrator
                # / wavefront driver threads through its re-sorts and
                # compaction — a ray keeps its stream wherever the
                # permutation lands it (the megakernel's positional index
                # IS the original lane there, since it never reorders).
                ray_index = lane_ref[:, :].astype(jnp.uint32)
            else:
                ray_index = (
                    jax.lax.broadcasted_iota(
                        jnp.int32, (1, block), 1
                    ).astype(jnp.uint32)
                    + jnp.uint32(pl.program_id(0) * block)
                )
        sphere_iota = jax.lax.broadcasted_iota(jnp.int32, (n_padded, block), 0)
        lanes = jax.lax.broadcasted_iota(jnp.int32, (leaf_size, block), 0)

        def winv(v):
            small = jnp.abs(v) < 1e-12
            return 1.0 / jnp.where(small, jnp.where(v < 0, -1e-12, 1e-12), v)

        def _octant_of(dx, dy, dz):
            # Majority vote over the packet's lanes (scalar dirs reduce
            # over one element): any octant's table is exact; matching
            # just shrinks best-t sooner.
            def bit(v, shift):
                positive = jnp.sum(jnp.where(v > 0.0, 1.0, 0.0))
                return jnp.where(
                    positive * 2.0 > float(jnp.size(v)),
                    jnp.int32(1 << shift),
                    jnp.int32(0),
                )

            return bit(dx, 0) | bit(dy, 1) | bit(dz, 2)

        if ordered:
            # Octant-ordered tables (sah builds): the BLAS node block is
            # EIGHT re-threadings stacked [8N]; each walk picks the table
            # whose near-first child order matches its (object-space)
            # direction octant.
            def blas_base(dx, dy, dz):
                return _octant_of(dx, dy, dz) * jnp.int32(n_nodes)
        else:
            def blas_base(dx, dy, dz):
                return jnp.int32(0)

        if tlas_ordered:
            # Same trick one level up: the TLAS node block is stacked
            # [8M] with the axis-by-depth near-first orders
            # (mesh.TlasTopology.octant_*); world-space direction octant
            # picks the table.
            def tlas_base(dx, dy, dz):
                return _octant_of(dx, dy, dz) * jnp.int32(tlas_nodes)
        else:
            def tlas_base(dx, dy, dz):
                return jnp.int32(0)

        def walk_step(node, obase, ox, oy, oz, dx, dy, dz, invx, invy,
                      invz, limit):
            """One threaded-BVH step shared by BOTH in-kernel walks.

            Slab-tests the node and advances the skip-link cursor. The
            [leaf_size, BR] Möller–Trumbore test lives in ``leaf_tcand``
            and runs only under a scalar branch at the call sites
            (``do_leaf`` = is_leaf & hit_any — the whole block walks the
            same node, so the predicate is scalar): internal nodes and
            culled subtrees skip the walk's dominant vector work entirely.
            ``obase`` is the walk's octant-table row offset (0 when the
            build ships a single canonical order); skip links are local,
            so only the reads offset. Returns (next_node, leaf start,
            leaf count, do_leaf).
            """
            (nlx, nly, nlz, nhx, nhy, nhz), nskip, start, count = (
                blas_node(obase + node)
            )
            lox = (nlx - ox) * invx
            hix = (nhx - ox) * invx
            loy = (nly - oy) * invy
            hiy = (nhy - oy) * invy
            loz = (nlz - oz) * invz
            hiz = (nhz - oz) * invz
            tnear = jnp.maximum(
                jnp.maximum(jnp.minimum(lox, hix), jnp.minimum(loy, hiy)),
                jnp.minimum(loz, hiz),
            )
            tfar = jnp.minimum(
                jnp.minimum(jnp.maximum(lox, hix), jnp.maximum(loy, hiy)),
                jnp.maximum(loz, hiz),
            )
            packet_hit = (tfar >= jnp.maximum(tnear, 0.0)) & (tnear < limit)
            hit_any = jnp.any(packet_hit)
            is_leaf = count > 0
            next_node = jnp.where(
                hit_any,
                jnp.where(is_leaf, nskip, node + 1),
                nskip,
            )
            return next_node, start, count, is_leaf & hit_any

        def leaf_tcand(start, count, ox, oy, oz, dx, dy, dz):
            """Möller–Trumbore over the aligned leaf slot at ``start``.

            Direction components may be [1, BR] vectors (nearest) or
            scalars (shadow rays toward the uniform sun). Returns
            (tri_hit [L, BR], t_cand [L, BR]).
            """
            v0b = v0_ref[pl.dslice(start, leaf_size), :]
            e1b = e1_ref[pl.dslice(start, leaf_size), :]
            e2b = e2_ref[pl.dslice(start, leaf_size), :]
            v0x, v0y, v0z = v0b[:, 0:1], v0b[:, 1:2], v0b[:, 2:3]
            e1x, e1y, e1z = e1b[:, 0:1], e1b[:, 1:2], e1b[:, 2:3]
            e2x, e2y, e2z = e2b[:, 0:1], e2b[:, 1:2], e2b[:, 2:3]
            pvx = dy * e2z - dz * e2y
            pvy = dz * e2x - dx * e2z
            pvz = dx * e2y - dy * e2x
            det = e1x * pvx + e1y * pvy + e1z * pvz
            inv_det = 1.0 / jnp.where(
                jnp.abs(det) < BVH_DONE_EPS, BVH_DONE_EPS, det
            )
            tvx, tvy, tvz = ox - v0x, oy - v0y, oz - v0z
            u = (tvx * pvx + tvy * pvy + tvz * pvz) * inv_det
            qvx = tvy * e1z - tvz * e1y
            qvy = tvz * e1x - tvx * e1z
            qvz = tvx * e1y - tvy * e1x
            v = (dx * qvx + dy * qvy + dz * qvz) * inv_det
            tt = (e2x * qvx + e2y * qvy + e2z * qvz) * inv_det
            tri_hit = (
                (jnp.abs(det) > BVH_DONE_EPS)
                & (u >= 0.0)
                & (v >= 0.0)
                & (u + v <= 1.0)
                & (tt > EPS)
                & (lanes < count)
            )
            t_cand = jnp.where(tri_hit, tt, INF)
            return tri_hit, t_cand

        def world_cull(k, wox, woy, woz, wix, wiy, wiz, limit_t):
            """Block-wide test of the untransformed rays against instance
            k's world AABB (SMEM cols 13..18); returns a scalar bool."""
            lox = (inst_ref[k, 13] - wox) * wix
            hix = (inst_ref[k, 16] - wox) * wix
            loy = (inst_ref[k, 14] - woy) * wiy
            hiy = (inst_ref[k, 17] - woy) * wiy
            loz = (inst_ref[k, 15] - woz) * wiz
            hiz = (inst_ref[k, 18] - woz) * wiz
            near = jnp.maximum(
                jnp.maximum(jnp.minimum(lox, hix), jnp.minimum(loy, hiy)),
                jnp.minimum(loz, hiz),
            )
            far = jnp.minimum(
                jnp.minimum(jnp.maximum(lox, hix), jnp.maximum(loy, hiy)),
                jnp.maximum(loz, hiz),
            )
            return jnp.any((far >= jnp.maximum(near, 0.0)) & (near < limit_t))

        def mesh_nearest(o, d, seed_t):
            """Nearest mesh hit over all instances.

            ``seed_t`` [1, BR] seeds the per-lane best-t (the same bounce's
            sphere/plane hit, -INF for dead lanes): walks the seed already
            beats are culled, dead lanes never drive a packet, and a mesh
            miss returns t == seed_t (callers compare with a strict <).
            Returns (t [1,BR], world normal [3 x (1,BR)], albedo
            [3 x (1,BR)]). Same walk as _bvh_instanced_kernel_factory with
            the winning triangle's normal and the instance albedo tracked
            in-kernel.
            """
            wox, woy, woz = o[0:1, :], o[1:2, :], o[2:3, :]
            wdx, wdy, wdz = d[0:1, :], d[1:2, :], d[2:3, :]
            wix, wiy, wiz = winv(wdx), winv(wdy), winv(wdz)

            def per_instance(k, carry):
                # Pool mode: the sweep bounds below already restrict k to
                # the block's frame window (the table is fid-major), so
                # only window instances get here; lanes from the OTHER
                # frame of a mixed window are packet-culled from this
                # instance's walk (slab limit -INF, like dead lanes) and
                # barred from the best-t update.
                match = (fid_row == inst_ref[k, 22]) if pool_io else None
                best_t, bnx, bny, bnz, bar, bag, bab, bslot = carry
                # The winning instance's SLOT label (within-frame in pool
                # mode): the quant tiers' packed-key candidate — a lane
                # that hit instance X bounces off X's surface, so X IS
                # the next ray's nearest-entry overlapped instance.
                if pool_io:
                    slot_of_k = k.astype(jnp.float32) - fid_row * jnp.float32(
                        k_per_frame
                    )
                else:
                    slot_of_k = k.astype(jnp.float32)
                r00, r01, r02 = inst_ref[k, 0], inst_ref[k, 1], inst_ref[k, 2]
                r10, r11, r12 = inst_ref[k, 3], inst_ref[k, 4], inst_ref[k, 5]
                r20, r21, r22 = inst_ref[k, 6], inst_ref[k, 7], inst_ref[k, 8]
                tx, ty, tz = inst_ref[k, 9], inst_ref[k, 10], inst_ref[k, 11]
                inv_s = inst_ref[k, 12]
                ar, ag, ab = inst_ref[k, 19], inst_ref[k, 20], inst_ref[k, 21]
                limit0 = (
                    jnp.where(match, best_t, -INF)
                    if pool_io else best_t
                )
                touch = world_cull(
                    k, wox, woy, woz, wix, wiy, wiz, limit0
                )

                sx, sy, sz = wox - tx, woy - ty, woz - tz
                ox = (sx * r00 + sy * r10 + sz * r20) * inv_s
                oy = (sx * r01 + sy * r11 + sz * r21) * inv_s
                oz = (sx * r02 + sy * r12 + sz * r22) * inv_s
                dx = (wdx * r00 + wdy * r10 + wdz * r20) * inv_s
                dy = (wdx * r01 + wdy * r11 + wdz * r21) * inv_s
                dz = (wdx * r02 + wdy * r12 + wdz * r22) * inv_s
                invx, invy, invz = winv(dx), winv(dy), winv(dz)
                obase = blas_base(dx, dy, dz)

                def cond(walk):
                    return walk[0] < n_nodes

                def body(walk):
                    (node, best_t, bnx, bny, bnz, bar_, bag_, bab_,
                     bslot_) = walk
                    walk_limit = (
                        jnp.where(match, best_t, -INF)
                        if match is not None else best_t
                    )
                    next_node, start, count, do_leaf = walk_step(
                        node, obase, ox, oy, oz, dx, dy, dz, invx, invy,
                        invz, walk_limit,
                    )

                    def leaf_pass():
                        _tri_hit, t_cand = leaf_tcand(
                            start, count, ox, oy, oz, dx, dy, dz
                        )
                        t_leaf = jnp.min(t_cand, axis=0, keepdims=True)
                        local = jnp.min(
                            jnp.where(t_cand == t_leaf, lanes, leaf_size),
                            axis=0,
                            keepdims=True,
                        )
                        # Winning row's OBJECT normal via a one-hot reduce
                        # (exactly one row: the first tying lane).
                        nb = nrm_ref[pl.dslice(start, leaf_size), :]
                        winner = (lanes == local).astype(jnp.float32)
                        nox = jnp.sum(
                            winner * nb[:, 0:1], axis=0, keepdims=True
                        )
                        noy = jnp.sum(
                            winner * nb[:, 1:2], axis=0, keepdims=True
                        )
                        noz = jnp.sum(
                            winner * nb[:, 2:3], axis=0, keepdims=True
                        )
                        return t_leaf, nox, noy, noz

                    def leaf_skip():
                        zero = jnp.zeros((1, block), jnp.float32)
                        return (
                            jnp.full((1, block), INF, jnp.float32),
                            zero, zero, zero,
                        )

                    t_leaf, nox, noy, noz = jax.lax.cond(
                        do_leaf, leaf_pass, leaf_skip
                    )
                    closer = t_leaf < best_t
                    if match is not None:
                        # leaf_tcand is limit-agnostic, so a mismatched
                        # lane can produce a finite t_leaf off another
                        # frame's geometry — bar it here.
                        closer = closer & match
                    # Object -> world (rigid): w_i = sum_j R[i][j] n_j.
                    wnx = r00 * nox + r01 * noy + r02 * noz
                    wny = r10 * nox + r11 * noy + r12 * noz
                    wnz = r20 * nox + r21 * noy + r22 * noz
                    best_t = jnp.where(closer, t_leaf, best_t)
                    bnx = jnp.where(closer, wnx, bnx)
                    bny = jnp.where(closer, wny, bny)
                    bnz = jnp.where(closer, wnz, bnz)
                    bar_ = jnp.where(closer, ar, bar_)
                    bag_ = jnp.where(closer, ag, bag_)
                    bab_ = jnp.where(closer, ab, bab_)
                    bslot_ = jnp.where(closer, slot_of_k, bslot_)
                    return (
                        next_node, best_t, bnx, bny, bnz, bar_, bag_, bab_,
                        bslot_,
                    )

                enter = 1 if (ordered and n_nodes > 1) else 0
                node0 = jnp.where(
                    touch, jnp.int32(enter), jnp.int32(n_nodes)
                )
                walked = jax.lax.while_loop(
                    cond, body,
                    (node0, best_t, bnx, bny, bnz, bar, bag, bab, bslot),
                )
                return walked[1:]

            # Slot sentinel = "no mesh hit": matches the entry walk's
            # no-overlap sentinel, and stays put for dead lanes (their
            # -INF seed admits no update).
            slot_sentinel = jnp.float32(k_per_frame if pool_io else k_count)
            init = (
                seed_t,
                jnp.zeros((1, block), jnp.float32),
                jnp.zeros((1, block), jnp.float32),
                jnp.zeros((1, block), jnp.float32),
                jnp.zeros((1, block), jnp.float32),
                jnp.zeros((1, block), jnp.float32),
                jnp.zeros((1, block), jnp.float32),
                jnp.full((1, block), slot_sentinel, jnp.float32),
            )
            if use_tlas:
                # Two-level walk: threaded skip-link TLAS over instance
                # groups; a leaf hit runs the EXISTING per-instance BLAS
                # walk over its slot range. A block whose packet misses a
                # subtree's union AABB (or whose per-lane best-t already
                # beats its entry) jumps the whole subtree — the flat
                # K-cull sweep this replaces paid every instance every
                # block. Pool mode walks one frame's node window per
                # fori step; lanes of OTHER frames in a mixed block are
                # barred from driving nodes (limit -INF, like dead
                # lanes) exactly as they are barred from the instances.
                def tlas_walk_nearest(node0, node_end, frame_match, carry):
                    limit_of = (
                        (lambda c: jnp.where(frame_match, c[0], -INF))
                        if frame_match is not None
                        else (lambda c: c[0])
                    )
                    return tlas_walk(
                        node0, node_end, tlas_base(wdx, wdy, wdz),
                        wox, woy, woz, wix, wiy, wiz,
                        limit_of, per_instance, carry,
                    )

                if pool_io:
                    def per_frame(f, carry):
                        node0 = f * tlas_per_frame
                        return tlas_walk_nearest(
                            node0, node0 + tlas_per_frame,
                            fid_row == f.astype(jnp.float32), carry,
                        )

                    walked = jax.lax.fori_loop(
                        fid_lo_ref[0, 0], fid_hi_ref[0, 0] + 1,
                        per_frame, init,
                    )
                else:
                    walked = tlas_walk_nearest(
                        jnp.int32(0), jnp.int32(tlas_nodes), None, init
                    )
                best_t, bnx, bny, bnz, bar, bag, bab, bslot = walked
            else:
                (best_t, bnx, bny, bnz, bar, bag, bab,
                 bslot) = jax.lax.fori_loop(
                    k_sweep_lo if pool_io else 0,
                    k_sweep_hi if pool_io else k_count,
                    per_instance, init,
                )
            # Flip toward the incoming ray (matches mesh.intersect_instances).
            facing = (
                bnx * d[0:1, :] + bny * d[1:2, :] + bnz * d[2:3, :]
            ) < 0.0
            sign = jnp.where(facing, 1.0, -1.0)
            return (
                best_t, (bnx * sign, bny * sign, bnz * sign),
                (bar, bag, bab), bslot,
            )

        def mesh_occluded(o, occluded0):
            """Any-hit toward the (uniform) sun for shadow origins ``o``.

            ``occluded0`` [1, BR] pre-marks lanes whose result cannot
            matter (sphere-shadowed, dead, backfacing): they stop driving
            the walks via the best_t=-INF trick (same as
            _bvh_anyhit_kernel_factory) and come back as 1.
            """
            wox, woy, woz = o[0:1, :], o[1:2, :], o[2:3, :]
            # TRUE rank-0 scalars from SMEM: a [1,1] vector operand here
            # ends up needing a both-sublanes-and-lanes vector.broadcast
            # against the walk's [L, BR] intermediates, which Mosaic does
            # not implement; scalar-vector ops use scalar registers.
            sunx = sunsm_ref[0]
            suny = sunsm_ref[1]
            sunz = sunsm_ref[2]
            wix, wiy, wiz = winv(sunx), winv(suny), winv(sunz)

            def per_instance(k, occluded):
                # Pool mode: the sweep bounds restrict k to the block's
                # frame window; a mixed window's other-frame lanes behave
                # like already-occluded ones for the WALK (limit -INF:
                # they never drive a packet) and their spurious leaf hits
                # are masked out of the occlusion result.
                if pool_io:
                    match_f = (fid_row == inst_ref[k, 22]).astype(
                        jnp.float32
                    )
                else:
                    match_f = None
                r00, r01, r02 = inst_ref[k, 0], inst_ref[k, 1], inst_ref[k, 2]
                r10, r11, r12 = inst_ref[k, 3], inst_ref[k, 4], inst_ref[k, 5]
                r20, r21, r22 = inst_ref[k, 6], inst_ref[k, 7], inst_ref[k, 8]
                tx, ty, tz = inst_ref[k, 9], inst_ref[k, 10], inst_ref[k, 11]
                inv_s = inst_ref[k, 12]
                blocked = (
                    jnp.maximum(occluded, 1.0 - match_f)
                    if pool_io else occluded
                )
                limit = jnp.where(blocked > 0.0, -INF, INF)
                touch = world_cull(
                    k, wox, woy, woz, wix, wiy, wiz, limit
                )
                sx, sy, sz = wox - tx, woy - ty, woz - tz
                ox = (sx * r00 + sy * r10 + sz * r20) * inv_s
                oy = (sx * r01 + sy * r11 + sz * r21) * inv_s
                oz = (sx * r02 + sy * r12 + sz * r22) * inv_s
                # All-scalar transform of the (uniform) sun direction into
                # this instance's object space — stays in scalar registers.
                dx = (sunx * r00 + suny * r10 + sunz * r20) * inv_s
                dy = (sunx * r01 + suny * r11 + sunz * r21) * inv_s
                dz = (sunx * r02 + suny * r12 + sunz * r22) * inv_s
                invx, invy, invz = winv(dx), winv(dy), winv(dz)
                obase = blas_base(dx, dy, dz)

                def cond(walk):
                    return walk[0] < n_nodes

                def body(walk):
                    node, occluded = walk
                    # Occluded lanes stop driving the walk: their packet
                    # limit is -INF so no node can pass their slab test.
                    walk_blocked = (
                        jnp.maximum(occluded, 1.0 - match_f)
                        if match_f is not None else occluded
                    )
                    limit = jnp.where(walk_blocked > 0.0, -INF, INF)
                    next_node, start, count, do_leaf = walk_step(
                        node, obase, ox, oy, oz, dx, dy, dz, invx, invy,
                        invz, limit,
                    )
                    occ_add = jax.lax.cond(
                        do_leaf,
                        lambda: jnp.max(
                            jnp.where(
                                leaf_tcand(
                                    start, count, ox, oy, oz, dx, dy, dz
                                )[0],
                                1.0,
                                0.0,
                            ),
                            axis=0,
                            keepdims=True,
                        ),
                        lambda: jnp.zeros((1, block), jnp.float32),
                    )
                    if match_f is not None:
                        occ_add = occ_add * match_f
                    occluded = jnp.maximum(occluded, occ_add)
                    return next_node, occluded

                enter = 1 if (ordered and n_nodes > 1) else 0
                node0 = jnp.where(
                    touch, jnp.int32(enter), jnp.int32(n_nodes)
                )
                _, walked_occluded = jax.lax.while_loop(
                    cond, body, (node0, occluded)
                )
                return walked_occluded

            if use_tlas:
                # Same two-level shape as the nearest walk, with the
                # any-hit limit convention: lanes whose result cannot
                # matter (pre-occluded, other-frame in pool mode) carry
                # a -INF limit and never drive a node's packet test.
                def tlas_walk_occluded(node0, node_end, match_f, occ0):
                    def limit_of(c):
                        blocked = (
                            jnp.maximum(c[0], 1.0 - match_f)
                            if match_f is not None else c[0]
                        )
                        return jnp.where(blocked > 0.0, -INF, INF)

                    return tlas_walk(
                        node0, node_end, tlas_base(sunx, suny, sunz),
                        wox, woy, woz, wix, wiy, wiz,
                        limit_of,
                        lambda k, c: (per_instance(k, c[0]),),
                        (occ0,),
                    )[0]

                if pool_io:
                    def per_frame(f, occluded):
                        node0 = f * tlas_per_frame
                        return tlas_walk_occluded(
                            node0, node0 + tlas_per_frame,
                            (fid_row == f.astype(jnp.float32)).astype(
                                jnp.float32
                            ),
                            occluded,
                        )

                    return jax.lax.fori_loop(
                        fid_lo_ref[0, 0], fid_hi_ref[0, 0] + 1,
                        per_frame, occluded0,
                    )
                return tlas_walk_occluded(
                    jnp.int32(0), jnp.int32(tlas_nodes), None, occluded0
                )
            return jax.lax.fori_loop(
                k_sweep_lo if pool_io else 0,
                k_sweep_hi if pool_io else k_count,
                per_instance, occluded0,
            )

        throughput = jnp.ones((3, block), jnp.float32)
        radiance = jnp.zeros((3, block), jnp.float32)
        alive = jnp.ones((1, block), jnp.float32)

        def bounce_step(bounce, carry):
            o, d, throughput, radiance, alive = carry
            # -- nearest sphere hit (same math as _trace_kernel_factory) --
            dc = jax.lax.dot_general(
                c, d, contract_first, preferred_element_type=jnp.float32
            )
            oc = jax.lax.dot_general(
                c, o, contract_first, preferred_element_type=jnp.float32
            )
            od = jnp.sum(o * d, axis=0, keepdims=True)
            o_sq = jnp.sum(o * o, axis=0, keepdims=True)
            oc_dot_d = dc - od
            oc_sq = o_sq - 2.0 * oc + csq
            disc = oc_dot_d * oc_dot_d - (oc_sq - r2)
            valid = (disc > 0.0) & (r2 > 0.0)
            if fid_match is not None:
                valid = valid & fid_match
            sqrt_disc = jnp.sqrt(jnp.maximum(disc, 0.0))
            t0 = oc_dot_d - sqrt_disc
            t1 = oc_dot_d + sqrt_disc
            t_all = jnp.where(t0 > EPS, t0, jnp.where(t1 > EPS, t1, INF))
            t_all = jnp.where(valid, t_all, INF)
            t_sphere = jnp.min(t_all, axis=0, keepdims=True)
            idx = jnp.min(
                jnp.where(t_all == t_sphere, sphere_iota, n_padded),
                axis=0,
                keepdims=True,
            )
            idx = jnp.minimum(idx, n_padded - 1)

            # -- ground plane ---------------------------------------------
            d_y = d[1:2, :]
            o_y = o[1:2, :]
            denom = jnp.where(jnp.abs(d_y) < 1e-8, 1e-8, d_y)
            t_plane = -o_y / denom
            t_plane = jnp.where(
                (t_plane > EPS) & (jnp.abs(d_y) >= 1e-8), t_plane, INF
            )

            # -- mesh instances -------------------------------------------
            # Seed the walk with the sphere/plane hit (walks it beats are
            # culled per lane) and -INF for dead lanes (they never drive a
            # packet; INF is 1e30, so the downstream arithmetic on their
            # lanes stays finite and alive-masked).
            t_sp = jnp.minimum(t_sphere, t_plane)
            seed_t = jnp.where(alive > 0.5, t_sp, -INF)
            t_mesh, (mnx, mny, mnz), (mar, mag, mab), hit_slot = (
                mesh_nearest(o, d, seed_t)
            )

            is_plane = ((t_plane < t_sphere) & (t_mesh >= t_sp)).astype(
                jnp.float32
            )
            is_mesh = (t_mesh < t_sp).astype(jnp.float32)
            t = jnp.minimum(t_sp, t_mesh)
            hit = (t < INF).astype(jnp.float32)

            # -- sky on escape --------------------------------------------
            blend = jnp.clip(d[1:2, :], 0.0, 1.0)
            sun_cos_dir = jnp.sum(d * sun, axis=0, keepdims=True)
            sun_disc = jnp.where(sun_cos_dir > 0.9995, 8.0, 0.0)
            sky = (1.0 - blend) * sky_horizon + blend * sky_zenith
            sky = sky + sun_disc * sun_color
            radiance = radiance + throughput * sky * (alive * (1.0 - hit))

            alive = alive * hit
            p = o + d * t

            one_hot = (sphere_iota == idx).astype(jnp.float32)
            c_hit = jax.lax.dot_general(
                c, one_hot, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            r_hit = jnp.sum(radius * one_hot, axis=0, keepdims=True)
            albedo_hit = jax.lax.dot_general(
                albedo_t, one_hot, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            emission_hit = jax.lax.dot_general(
                emission_t, one_hot, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

            sphere_normal = (p - c_hit) / jnp.maximum(r_hit, 1e-6)
            plane_normal = jnp.concatenate(
                [
                    jnp.zeros((1, block), jnp.float32),
                    jnp.ones((1, block), jnp.float32),
                    jnp.zeros((1, block), jnp.float32),
                ],
                axis=0,
            )
            mesh_normal = jnp.concatenate([mnx, mny, mnz], axis=0)
            normal = (
                is_plane * plane_normal
                + is_mesh * mesh_normal
                + (1.0 - is_plane - is_mesh) * sphere_normal
            )

            checker = (
                jnp.floor(p[0:1, :]).astype(jnp.int32)
                + jnp.floor(p[2:3, :]).astype(jnp.int32)
            ) % 2
            checker_rgb = jnp.where(checker == 0, plane_a, plane_b)
            mesh_albedo = jnp.concatenate([mar, mag, mab], axis=0)
            albedo = (
                is_plane * checker_rgb
                + is_mesh * mesh_albedo
                + (1.0 - is_plane - is_mesh) * albedo_hit
            )
            emission = (1.0 - is_plane - is_mesh) * emission_hit
            radiance = radiance + throughput * emission * alive

            # -- sun NEE: sphere any-hit + mesh any-hit -------------------
            shadow_o = p + normal * (EPS * 4.0)
            oc_s = jax.lax.dot_general(
                c, shadow_o, contract_first, preferred_element_type=jnp.float32
            )
            od_s = jnp.sum(shadow_o * sun, axis=0, keepdims=True)
            osq_s = jnp.sum(shadow_o * shadow_o, axis=0, keepdims=True)
            ocd_s = dc_sun - od_s
            ocsq_s = osq_s - 2.0 * oc_s + csq
            disc_s = ocd_s * ocd_s - (ocsq_s - r2)
            valid_s = (disc_s > 0.0) & (r2 > 0.0)
            if fid_match is not None:
                valid_s = valid_s & fid_match
            t1_s = ocd_s + jnp.sqrt(jnp.maximum(disc_s, 0.0))
            shadowed = jnp.max(
                jnp.where(valid_s & (t1_s > EPS), 1.0, 0.0),
                axis=0,
                keepdims=True,
            )
            cos_sun = jnp.maximum(
                jnp.sum(normal * sun, axis=0, keepdims=True), 0.0
            )
            # Lanes whose shadow result cannot matter (sphere-shadowed,
            # dead, backfacing — their direct term is zero regardless)
            # stop driving the mesh any-hit walks.
            occluded0 = jnp.maximum(
                shadowed,
                jnp.maximum(
                    1.0 - alive, (cos_sun <= 0.0).astype(jnp.float32)
                ),
            )
            shadowed = mesh_occluded(shadow_o, occluded0)
            direct = (
                albedo * sun_color * (cos_sun * (1.0 - shadowed) * alive)
                / jnp.float32(jnp.pi)
            )
            radiance = radiance + throughput * direct

            # -- cosine-weighted resample (counter PCG) -------------------
            throughput = throughput * (alive * albedo + (1.0 - alive))
            counter = ray_index * jnp.uint32(2 * max_bounces + 2) + jnp.uint32(2) * bounce.astype(jnp.uint32)
            u1 = _uniform_from_hash(_pcg_hash(counter ^ seed))
            u2 = _uniform_from_hash(_pcg_hash((counter + jnp.uint32(1)) ^ seed))
            r = jnp.sqrt(u1)
            phi = jnp.float32(2.0 * jnp.pi) * u2
            x = r * jnp.cos(phi)
            y = r * jnp.sin(phi)
            z = jnp.sqrt(jnp.maximum(0.0, 1.0 - u1))
            helper_x = jnp.where(jnp.abs(normal[0:1, :]) > 0.9, 0.0, 1.0)
            helper_y = 1.0 - helper_x
            tx = helper_y * normal[2:3, :]
            ty = -helper_x * normal[2:3, :]
            tz = helper_x * normal[1:2, :] - helper_y * normal[0:1, :]
            tangent = jnp.concatenate([tx, ty, tz], axis=0)
            tangent = tangent / jnp.maximum(
                jnp.sqrt(jnp.sum(tangent * tangent, axis=0, keepdims=True)),
                1e-8,
            )
            bx = normal[1:2, :] * tangent[2:3, :] - normal[2:3, :] * tangent[1:2, :]
            by = normal[2:3, :] * tangent[0:1, :] - normal[0:1, :] * tangent[2:3, :]
            bz = normal[0:1, :] * tangent[1:2, :] - normal[1:2, :] * tangent[0:1, :]
            bitangent = jnp.concatenate([bx, by, bz], axis=0)
            new_d = x * tangent + y * bitangent + z * normal
            new_o = p + normal * (EPS * 4.0)
            live = alive > 0.5
            o = jnp.where(live, new_o, o)
            d = jnp.where(live, new_d, d)
            return (o, d, throughput, radiance, alive, hit_slot)

        if state_io or pool_io:
            # ONE bounce with streamed state: overwrite the in-kernel
            # initial state with the caller's, run bounce_step once at the
            # caller's bounce index, stream everything back out. Blocks
            # whose first lane is past the live count are all-dead (the
            # Morton sort / compaction puts dead lanes at the tail) and
            # pass state through untouched — bit-identical to what the
            # masked bounce computes for dead lanes, without paying for
            # the walks. Pool mode: the bounce index is a per-lane row
            # (mixed depths), consumed only by the RNG counter.
            throughput = thr_ref[:, :]
            alive = alive_ref[:, :]
            bounce_index = (
                bounce_row_ref[:, :] if pool_io else bounce_ref[0, 0]
            )
            block_start = pl.program_id(0) * block
            slot_sentinel = jnp.float32(k_per_frame if pool_io else k_count)
            o, d, throughput, radiance, alive, hit_slot = jax.lax.cond(
                block_start < live_ref[0, 0],
                lambda: bounce_step(
                    bounce_index, (o, d, throughput, radiance, alive)
                ),
                lambda: (
                    o, d, throughput, radiance, alive,
                    jnp.full((1, block), slot_sentinel, jnp.float32),
                ),
            )
            out_ref[:, :] = radiance
            o_out_ref[:, :] = o
            d_out_ref[:, :] = d
            thr_out_ref[:, :] = throughput
            alive_out_ref[:, :] = alive
            if use_tlas:
                # Fused coherence-key epilogue: the NEXT bounce's sort
                # key, derived from the post-bounce state while it is
                # still VMEM-resident (the separate XLA broadphase pass
                # this replaces re-read the full ray state from HBM).
                # The candidate component — the NEW ray's nearest-entry
                # overlapped instance, the strongest grouping signal for
                # floor-bounce packets — comes from an AABB-only TLAS
                # walk (node slabs + leaf instance-AABB entries, no BLAS
                # descent). Gated on the same live-count branch as the
                # bounce: skipped all-dead tail blocks key their
                # passthrough state with the sentinel candidate — all
                # dead, so the dead bit keeps them parked at the tail.
                eox, eoy, eoz = o[0:1, :], o[1:2, :], o[2:3, :]
                edx, edy, edz = d[0:1, :], d[1:2, :], d[2:3, :]
                eix, eiy, eiz = winv(edx), winv(edy), winv(edz)
                live_lane = alive > 0.5

                def entry_leaf(slot_offset):
                    def leaf_step(k, carry):
                        best_e, best_s = carry
                        lox = (inst_ref[k, 13] - eox) * eix
                        hix = (inst_ref[k, 16] - eox) * eix
                        loy = (inst_ref[k, 14] - eoy) * eiy
                        hiy = (inst_ref[k, 17] - eoy) * eiy
                        loz = (inst_ref[k, 15] - eoz) * eiz
                        hiz = (inst_ref[k, 18] - eoz) * eiz
                        near = jnp.maximum(
                            jnp.maximum(
                                jnp.minimum(lox, hix), jnp.minimum(loy, hiy)
                            ),
                            jnp.minimum(loz, hiz),
                        )
                        far = jnp.minimum(
                            jnp.minimum(
                                jnp.maximum(lox, hix), jnp.maximum(loy, hiy)
                            ),
                            jnp.maximum(loz, hiz),
                        )
                        overlap = far >= jnp.maximum(near, 0.0)
                        if pool_io:
                            overlap = overlap & (fid_row == inst_ref[k, 22])
                        entry = jnp.where(
                            overlap, jnp.maximum(near, 0.0), INF
                        )
                        improved = entry < best_e
                        best_e = jnp.where(improved, entry, best_e)
                        best_s = jnp.where(
                            improved,
                            (k - slot_offset).astype(jnp.float32),
                            best_s,
                        )
                        return best_e, best_s

                    return leaf_step

                sentinel = jnp.float32(k_per_frame if pool_io else k_count)
                # Packed-key tier: mesh-hit lanes already carry their
                # candidate (the nearest walk's winning slot), so they
                # stop driving the entry walk's packet descents.
                entry_lane = (
                    live_lane & (hit_slot >= sentinel) if quant
                    else live_lane
                )

                def entry_walk(node0, node_end, slot_offset, match, carry):
                    drive = (
                        entry_lane if match is None else entry_lane & match
                    )
                    return tlas_walk(
                        node0, node_end, tlas_base(edx, edy, edz),
                        eox, eoy, eoz, eix, eiy, eiz,
                        lambda c: jnp.where(drive, c[0], -INF),
                        entry_leaf(slot_offset), carry,
                    )
                entry_init = (
                    jnp.full((1, block), INF, jnp.float32),
                    jnp.full((1, block), sentinel, jnp.float32),
                )

                def run_entry_walk():
                    if pool_io:
                        def per_frame_entry(f, carry):
                            node0 = f * tlas_per_frame
                            return entry_walk(
                                node0, node0 + tlas_per_frame,
                                f * k_per_frame,
                                fid_row == f.astype(jnp.float32), carry,
                            )

                        return jax.lax.fori_loop(
                            fid_lo_ref[0, 0], fid_hi_ref[0, 0] + 1,
                            per_frame_entry, entry_init,
                        )
                    return entry_walk(
                        jnp.int32(0), jnp.int32(tlas_nodes), jnp.int32(0),
                        None, entry_init,
                    )

                # Final-bounce launches (state_io: the bounce index is a
                # uniform scalar) never have their key consumed — the
                # driver's loop ends — so skip the entry walk there and
                # key with the sentinel candidate. Pool mode cannot gate:
                # lanes sit at MIXED depths and the next pool iteration
                # always sorts by this column.
                want_candidates = block_start < live_ref[0, 0]
                if not pool_io:
                    want_candidates = want_candidates & (
                        bounce_ref[0, 0] < max_bounces - 1
                    )
                _, best_slot = jax.lax.cond(
                    want_candidates,
                    run_entry_walk,
                    lambda: entry_init,
                )
                if quant:
                    # Packed-key tier: lanes that HIT an instance take
                    # the nearest walk's winning slot as their candidate
                    # — a lane that hit X bounces off X's surface, so X
                    # is the new ray's nearest-entry overlap to first
                    # order — and STOP DRIVING the entry walk (see
                    # entry_drive below): packets dominated by mesh hits
                    # prune most of the second TLAS walk while plane/
                    # sphere-bounce lanes keep their exact candidates.
                    # Keys only order lanes, so per-lane results stay
                    # exact either way.
                    best_slot = jnp.where(
                        hit_slot < sentinel, hit_slot, best_slot
                    )
                key = coherence_key_u32(
                    o[0:1, :] + d[0:1, :],
                    o[1:2, :] + d[1:2, :],
                    o[2:3, :] + d[2:3, :],
                    d[0:1, :], d[1:2, :], d[2:3, :],
                    alive <= 0.5,
                    (fid_row.astype(jnp.int32) if pool_io
                     else jnp.zeros((1, block), jnp.int32)),
                    best_slot.astype(jnp.int32),
                    keysm_ref[0], keysm_ref[1], keysm_ref[2],
                    keysm_ref[3], keysm_ref[4], keysm_ref[5],
                )
                key_out_ref[:, :] = key.astype(jnp.int32)
        else:
            # bounce_step also returns the hit-instance slot (the
            # streamed-state kernels' packed-key candidate); the
            # megakernel's loop carry drops it.
            _, _, _, radiance, _ = jax.lax.fori_loop(
                0, max_bounces,
                lambda b, carry: bounce_step(b, carry)[:5],
                (o, d, throughput, radiance, alive),
            )
            out_ref[:, :] = radiance

    return kernel


def _tlas_node_arrays(topology, node_lo, node_hi, ordered: bool):
    """TLAS node-table arrays: the canonical single order, or the eight
    axis-by-depth near-first re-threadings (bounds gathered through the
    static octant_perm) when the walk is octant-ordered."""
    if not ordered:
        return (
            node_lo, node_hi, topology.skip, topology.first,
            topology.count,
        )
    perm = jnp.asarray(topology.octant_perm)
    return (
        node_lo[perm], node_hi[perm], topology.octant_skip,
        topology.octant_first, topology.octant_count,
    )


def _blas_node_arrays(bounds_min, bounds_max, skip, first, count, octant):
    """(lo, hi, skip, first, count, ordered) for the BLAS node block: the
    octant-stacked near-first tables when the build ships them
    (mesh.OctantTables — sah builds), else the canonical single order.
    ``ordered`` is static (None-ness of the pytree), so each case is its
    own compiled kernel."""
    if octant is None:
        return bounds_min, bounds_max, skip, first, count, False
    return (
        octant.bounds_min, octant.bounds_max, octant.skip, octant.first,
        octant.count, True,
    )


def _node_table_operands(lo, hi, skip, first, count, *, quant: int,
                         first_unit: int):
    """(operands, specs) for one node-table block in either format.

    The ONE packing site all three mesh drivers share: fp32 mode ships
    the five classic SMEM refs; quantized mode ships the packed
    bq/meta/grid triple from ``mesh.quantize_node_tables`` (static BLAS
    tables constant-fold under jit; traced TLAS bounds quantize as cheap
    per-frame arithmetic).
    """
    whole = lambda i: (0, 0)  # noqa: E731
    flat = lambda i: (0,)  # noqa: E731
    if quant:
        from tpu_render_cluster.render.mesh import quantize_node_tables

        bq, meta, grid = quantize_node_tables(
            lo, hi, skip, first, count, quant=quant, first_unit=first_unit
        )
        return (bq, meta, grid), [
            pl.BlockSpec(bq.shape, whole, memory_space=pltpu.SMEM),
            pl.BlockSpec(meta.shape, flat, memory_space=pltpu.SMEM),
            pl.BlockSpec((6,), flat, memory_space=pltpu.SMEM),
        ]
    lo = jnp.asarray(lo, jnp.float32)
    hi = jnp.asarray(hi, jnp.float32)
    skip = jnp.asarray(skip, jnp.int32)
    first = jnp.asarray(first, jnp.int32)
    count = jnp.asarray(count, jnp.int32)
    n = skip.shape[0]
    return (lo, hi, skip, first, count), [
        pl.BlockSpec(lo.shape, whole, memory_space=pltpu.SMEM),
        pl.BlockSpec(hi.shape, whole, memory_space=pltpu.SMEM),
        pl.BlockSpec((n,), flat, memory_space=pltpu.SMEM),
        pl.BlockSpec((n,), flat, memory_space=pltpu.SMEM),
        pl.BlockSpec((n,), flat, memory_space=pltpu.SMEM),
    ]


@functools.partial(
    jax.jit,
    static_argnames=(
        "max_bounces", "interpret", "use_tlas", "tlas_leaf", "tlas_block",
        "quant",
    ),
)
def _trace_fused_mesh(
    origins, directions, centers, radii, albedo, emission,
    sun_direction, sun_color, sky_horizon, sky_zenith,
    plane_albedo_a, plane_albedo_b, seed,
    rotation, translation, scale, inst_albedo,
    v0, e1, e2, normal, bounds_min, bounds_max, skip, first, count,
    octant=None,
    *, max_bounces: int, interpret: bool, use_tlas: bool = False,
    tlas_leaf: int = 4, tlas_block: int = 256, quant: int = 0,
):
    from tpu_render_cluster.render.mesh import LEAF_SIZE

    # Pad lanes must provably MISS (far origin, perpendicular unit dir):
    # zero-padded directions would degenerate the slab tests and strip the
    # packet culling from the final block (see _pad_rays_to_miss). The
    # TLAS variant blocks rays at its own (narrower) packet width —
    # threaded in as a static arg (env tiers are read OUTSIDE traced
    # functions; the env-tiers lint pass pins this).
    block = tlas_block if use_tlas else BVH_BLOCK_R
    o_t, d_t, rays, padded_rays = _pad_rays_to_miss(
        origins, directions, block
    )

    n = centers.shape[0]
    padded_n = -(-n // _SUBLANE) * _SUBLANE
    sphere_pad = padded_n - n
    c_t = jnp.pad(centers, ((0, sphere_pad), (0, 0))).T
    radii_p = jnp.pad(radii, (0, sphere_pad))
    r2 = (radii_p * radii_p)[:, None]
    csq = jnp.sum(c_t * c_t, axis=0)[:, None]
    rad = radii_p[:, None]
    albedo_t = jnp.pad(albedo, ((0, sphere_pad), (0, 0))).T
    emission_t = jnp.pad(emission, ((0, sphere_pad), (0, 0))).T
    dc_sun = (c_t.T @ sun_direction)[:, None]

    params = jnp.zeros((8, 3), jnp.float32)
    params = params.at[0].set(sun_direction)
    params = params.at[1].set(sun_color)
    params = params.at[2].set(sky_horizon)
    params = params.at[3].set(sky_zenith)
    params = params.at[4].set(plane_albedo_a)
    params = params.at[5].set(plane_albedo_b)
    seed_arr = jnp.asarray(seed, jnp.int32).reshape(1, 1)

    n_nodes = skip.shape[0]
    k_count = rotation.shape[0]
    if use_tlas:
        # Slot-assign instances by Morton order of their world-AABB
        # centers (ray-independent, so every launch of this frame — any
        # region, any tier — derives the same table order) and build the
        # per-frame TLAS node unions over the sorted AABBs. Topology is
        # static/memoized; bounds are cheap traced arithmetic.
        from tpu_render_cluster.render.mesh import (
            cached_tlas_topology,
            instance_morton_order,
            tlas_node_bounds,
        )

        # ONE table build, slot-ordered by a row gather (every table
        # column is a per-instance row-wise function, so gathering rows
        # IS rebuilding on gathered inputs — exactly, same f32 ops).
        table = _instance_table(
            rotation, translation, scale, bounds_min, bounds_max,
            inst_albedo,
        )
        lo_w, hi_w = table[:, 13:16], table[:, 16:19]
        order = instance_morton_order(lo_w, hi_w)
        inst_table = table[order]
        topology = cached_tlas_topology(k_count, tlas_leaf)
        node_lo, node_hi = tlas_node_bounds(
            topology, lo_w[order], hi_w[order]
        )
        tlas_nodes = int(topology.skip.shape[0])
        quant = resolve_bvh_quant(
            quant,
            (n_nodes, v0.shape[0] // LEAF_SIZE, LEAF_SIZE),
            (tlas_nodes, k_count, tlas_leaf),
        )
        tlas_operands, tlas_specs = _node_table_operands(
            *_tlas_node_arrays(topology, node_lo, node_hi, octant is not None),
            quant=quant, first_unit=1,
        )
    else:
        inst_table = _instance_table(
            rotation, translation, scale, bounds_min, bounds_max,
            inst_albedo,
        )
        quant = resolve_bvh_quant(
            quant, (n_nodes, v0.shape[0] // LEAF_SIZE, LEAF_SIZE)
        )
        tlas_operands, tlas_specs = (), []
        tlas_nodes = 0
    blas_arrays = _blas_node_arrays(
        bounds_min, bounds_max, skip, first, count, octant
    )
    ordered = blas_arrays[5]
    blas_operands, blas_specs = _node_table_operands(
        *blas_arrays[:5], quant=quant, first_unit=LEAF_SIZE,
    )

    grid = (padded_rays // block,)
    whole = lambda i: (0, 0)  # noqa: E731
    flat = lambda i: (0,)  # noqa: E731
    out = pl.pallas_call(
        _mesh_trace_kernel_factory(
            max_bounces, padded_n, n_nodes, LEAF_SIZE, k_count,
            use_tlas=use_tlas, tlas_nodes=tlas_nodes, quant=quant,
            ordered=ordered, tlas_ordered=use_tlas and ordered,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1), whole, memory_space=pltpu.SMEM),
            pl.BlockSpec((3, block), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((3, block), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((3, padded_n), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((padded_n, 1), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((padded_n, 1), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((padded_n, 1), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((3, padded_n), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((3, padded_n), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((padded_n, 1), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((8, 3), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((3,), flat, memory_space=pltpu.SMEM),
            pl.BlockSpec(inst_table.shape, whole, memory_space=pltpu.SMEM),
            pl.BlockSpec(v0.shape, whole, memory_space=pltpu.VMEM),
            pl.BlockSpec(e1.shape, whole, memory_space=pltpu.VMEM),
            pl.BlockSpec(e2.shape, whole, memory_space=pltpu.VMEM),
            pl.BlockSpec(normal.shape, whole, memory_space=pltpu.VMEM),
        ] + blas_specs + tlas_specs,
        out_specs=[
            pl.BlockSpec((3, block), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_shape=[jax.ShapeDtypeStruct((3, padded_rays), jnp.float32)],
        interpret=interpret,
    )(seed_arr, o_t, d_t, c_t, r2, csq, rad, albedo_t, emission_t, dc_sun,
      params, sun_direction, inst_table, v0, e1, e2, normal,
      *blas_operands, *tlas_operands)[0]
    return out.T[:rays]


def _mesh_bounce_io(
    origins, directions, throughput, alive, lane, live_count, seed, bounce,
    centers, radii, albedo, emission,
    sun_direction, sun_color, sky_horizon, sky_zenith,
    plane_albedo_a, plane_albedo_b,
    rotation, translation, scale, inst_albedo,
    v0, e1, e2, normal, bounds_min, bounds_max, skip, first, count,
    octant=None,
    *, total_bounces: int, interpret: bool, use_tlas: bool = False,
    tlas_leaf: int = 4, tlas_block: int = 256, quant: int = 0,
):
    from tpu_render_cluster.render.mesh import LEAF_SIZE

    # The TLAS variant blocks rays at its own narrower packet width —
    # threaded in by the caller (env tiers resolve outside traces).
    block = tlas_block if use_tlas else BVH_BLOCK_R
    o_t, d_t, rays, padded_rays = _pad_rays_to_miss(
        origins, directions, block
    )
    ray_pad = padded_rays - rays
    thr_t = jnp.pad(throughput, ((0, ray_pad), (0, 0))).T  # [3, Rp]
    # Pad lanes are DEAD: with their guaranteed-miss rays they never drive
    # a walk and their contribution stays zero.
    alive_t = jnp.pad(alive.astype(jnp.float32), (0, ray_pad))[None, :]
    lane_t = jnp.pad(lane.astype(jnp.int32), (0, ray_pad))[None, :]

    n = centers.shape[0]
    padded_n = -(-n // _SUBLANE) * _SUBLANE
    sphere_pad = padded_n - n
    c_t = jnp.pad(centers, ((0, sphere_pad), (0, 0))).T
    radii_p = jnp.pad(radii, (0, sphere_pad))
    r2 = (radii_p * radii_p)[:, None]
    csq = jnp.sum(c_t * c_t, axis=0)[:, None]
    rad = radii_p[:, None]
    albedo_t = jnp.pad(albedo, ((0, sphere_pad), (0, 0))).T
    emission_t = jnp.pad(emission, ((0, sphere_pad), (0, 0))).T
    dc_sun = (c_t.T @ sun_direction)[:, None]

    params = jnp.zeros((8, 3), jnp.float32)
    params = params.at[0].set(sun_direction)
    params = params.at[1].set(sun_color)
    params = params.at[2].set(sky_horizon)
    params = params.at[3].set(sky_zenith)
    params = params.at[4].set(plane_albedo_a)
    params = params.at[5].set(plane_albedo_b)
    seed_arr = jnp.asarray(seed, jnp.int32).reshape(1, 1)
    bounce_arr = jnp.asarray(bounce, jnp.int32).reshape(1, 1)
    live_arr = jnp.asarray(live_count, jnp.int32).reshape(1, 1)

    n_nodes = skip.shape[0]
    k_count = rotation.shape[0]
    if use_tlas:
        # TLAS slot order: Morton over instance world-AABB centers —
        # ray-INDEPENDENT (unlike the anchor sort below), so a region
        # launch and the whole-frame launch derive identical tables and
        # node bounds, keeping tiled-equals-untiled exact. Front-to-back
        # seeding is subsumed by the walk's per-node entry-vs-best-t cull.
        from tpu_render_cluster.render.mesh import (
            cached_tlas_topology,
            instance_morton_order,
            tlas_node_bounds,
        )

        # ONE table build, slot-ordered by a row gather (every table
        # column is a per-instance row-wise function, so gathering rows
        # IS rebuilding on gathered inputs — exactly, same f32 ops).
        table = _instance_table(
            rotation, translation, scale, bounds_min, bounds_max,
            inst_albedo,
        )
        lo_w, hi_w = table[:, 13:16], table[:, 16:19]
        order = instance_morton_order(lo_w, hi_w)
        inst_table = table[order]
        topology = cached_tlas_topology(k_count, tlas_leaf)
        node_lo, node_hi = tlas_node_bounds(
            topology, lo_w[order], hi_w[order]
        )
        key_lo, key_inv = mesh_key_bounds(lo_w, hi_w)
        tlas_nodes = int(topology.skip.shape[0])
        quant = resolve_bvh_quant(
            quant,
            (n_nodes, v0.shape[0] // LEAF_SIZE, LEAF_SIZE),
            (tlas_nodes, k_count, tlas_leaf),
        )
        tlas_operands, tlas_specs = _node_table_operands(
            *_tlas_node_arrays(topology, node_lo, node_hi, octant is not None),
            quant=quant, first_unit=1,
        )
        extra_operands = (
            *tlas_operands, jnp.concatenate([key_lo, key_inv]),
        )
    else:
        # Front-to-back instance order (pure data reordering — normals/
        # albedo are tracked in-kernel, so results are order-invariant):
        # near instances set small best-t early and the per-lane walk
        # culls most of the rest. Dead lanes are parked at 1e7 by the
        # integrator and must not drag the anchor.
        valid = (jnp.abs(origins) < 1e6).all(axis=1) & alive
        anchor_point = jnp.sum(
            jnp.where(valid[:, None], origins, 0.0), axis=0
        ) / jnp.maximum(jnp.sum(valid), 1)
        near_first = jnp.argsort(
            jnp.sum((translation - anchor_point[None, :]) ** 2, axis=1)
        )
        inst_table = _instance_table(
            rotation[near_first], translation[near_first],
            scale[near_first],
            bounds_min, bounds_max, inst_albedo[near_first],
        )
        quant = resolve_bvh_quant(
            quant, (n_nodes, v0.shape[0] // LEAF_SIZE, LEAF_SIZE)
        )
        tlas_specs = []
        extra_operands = ()
        tlas_nodes = 0
    blas_arrays = _blas_node_arrays(
        bounds_min, bounds_max, skip, first, count, octant
    )
    ordered = blas_arrays[5]
    blas_operands, blas_specs = _node_table_operands(
        *blas_arrays[:5], quant=quant, first_unit=LEAF_SIZE,
    )

    grid = (padded_rays // block,)
    whole = lambda i: (0, 0)  # noqa: E731
    flat = lambda i: (0,)  # noqa: E731
    ray_block = pl.BlockSpec(
        (3, block), lambda i: (0, i), memory_space=pltpu.VMEM
    )
    row_block = pl.BlockSpec(
        (1, block), lambda i: (0, i), memory_space=pltpu.VMEM
    )
    extra_specs = (
        tlas_specs + [pl.BlockSpec((6,), flat, memory_space=pltpu.SMEM)]
        if use_tlas
        else []
    )
    key_out_specs = [row_block] if use_tlas else []
    key_out_shapes = (
        [jax.ShapeDtypeStruct((1, padded_rays), jnp.int32)]
        if use_tlas else []
    )
    results = pl.pallas_call(
        _mesh_trace_kernel_factory(
            total_bounces, padded_n, n_nodes, LEAF_SIZE, k_count,
            state_io=True, use_tlas=use_tlas, tlas_nodes=tlas_nodes,
            quant=quant, ordered=ordered,
            tlas_ordered=use_tlas and ordered,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1), whole, memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), whole, memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), whole, memory_space=pltpu.SMEM),
            ray_block,
            ray_block,
            ray_block,
            row_block,
            row_block,
            pl.BlockSpec((3, padded_n), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((padded_n, 1), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((padded_n, 1), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((padded_n, 1), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((3, padded_n), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((3, padded_n), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((padded_n, 1), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((8, 3), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((3,), flat, memory_space=pltpu.SMEM),
            pl.BlockSpec(inst_table.shape, whole, memory_space=pltpu.SMEM),
            pl.BlockSpec(v0.shape, whole, memory_space=pltpu.VMEM),
            pl.BlockSpec(e1.shape, whole, memory_space=pltpu.VMEM),
            pl.BlockSpec(e2.shape, whole, memory_space=pltpu.VMEM),
            pl.BlockSpec(normal.shape, whole, memory_space=pltpu.VMEM),
        ] + blas_specs + extra_specs,
        out_specs=[ray_block, ray_block, ray_block, ray_block, row_block]
        + key_out_specs,
        out_shape=[
            jax.ShapeDtypeStruct((3, padded_rays), jnp.float32),
            jax.ShapeDtypeStruct((3, padded_rays), jnp.float32),
            jax.ShapeDtypeStruct((3, padded_rays), jnp.float32),
            jax.ShapeDtypeStruct((3, padded_rays), jnp.float32),
            jax.ShapeDtypeStruct((1, padded_rays), jnp.float32),
        ] + key_out_shapes,
        interpret=interpret,
    )(seed_arr, bounce_arr, live_arr, o_t, d_t, thr_t, alive_t, lane_t,
      c_t, r2, csq, rad,
      albedo_t, emission_t, dc_sun, params, sun_direction, inst_table,
      v0, e1, e2, normal, *blas_operands,
      *extra_operands)
    contrib, o2, d2, thr2, alive2 = results[:5]
    key2 = results[5][0, :rays] if use_tlas else None
    return (
        contrib.T[:rays],
        o2.T[:rays],
        d2.T[:rays],
        thr2.T[:rays],
        alive2[0, :rays] > 0.5,
        key2,
    )


def mesh_bounce_pallas(
    scene, mesh, origins, directions, throughput, alive, seed, bounce,
    *, total_bounces: int, lane=None, live_count=None, use_tlas=None,
    quant: int | None = None, tlas_block: int | None = None,
):
    """One fused path-trace bounce for deep-walk mesh scenes.

    The megakernel's bounce_step as a single launch with path state
    streamed in/out, so integrator.trace_paths can re-sort rays between
    bounces (packet coherence) without paying per-bounce XLA glue —
    separate sphere/shadow kernels, threefry RNG, and a dozen elementwise
    HBM round trips. ``lane`` carries each ray's ORIGINAL lane id — the
    RNG counter, so a ray's stream survives the re-sort/compaction
    permutations; ``live_count`` is the number of leading live lanes
    (dead lanes must be sorted to the tail), letting all-dead tail
    blocks skip the bounce. Defaults: positional lanes, nothing skipped.

    ``use_tlas`` (None = the ``TRC_TLAS`` env tier) selects the
    two-level TLAS kernel variant, which also emits the fused coherence
    sort key of the POST-bounce state. Returns (radiance contribution
    [R, 3], new origins, new directions, new throughput, new alive,
    key [R] int32 — None on the flat variant).
    """
    n = origins.shape[0]
    if lane is None:
        lane = jnp.arange(n, dtype=jnp.int32)
    if live_count is None:
        live_count = jnp.int32(n)
    bvh = mesh.bvh
    instances = mesh.instances
    return _mesh_bounce_io(
        origins, directions, throughput, alive, lane, live_count, seed,
        bounce,
        scene.centers, scene.radii, scene.albedo, scene.emission,
        scene.sun_direction, scene.sun_color, scene.sky_horizon,
        scene.sky_zenith, scene.plane_albedo_a, scene.plane_albedo_b,
        instances.rotation, instances.translation, instances.scale,
        instances.albedo,
        bvh.v0, bvh.e1, bvh.e2, bvh.normal,
        bvh.bounds_min, bvh.bounds_max, bvh.skip, bvh.first, bvh.count,
        bvh.octant,
        total_bounces=total_bounces, interpret=_interpret(),
        use_tlas=use_tlas_for(instances.translation.shape[0], use_tlas),
        tlas_leaf=tlas_leaf_size(),
        tlas_block=tlas_block_r() if tlas_block is None else int(tlas_block),
        quant=bvh_quant_mode() if quant is None else int(quant),
    )


def trace_paths_fused_mesh(
    scene, mesh, origins, directions, seed, *, max_bounces: int,
    use_tlas=None, quant: int | None = None,
):
    """Fused megakernel path trace for mesh scenes; drop-in for
    integrator.trace_paths with a MeshSet. Same physics as the XLA bounce
    scan + per-pass kernels; different (in-kernel counter PCG) RNG stream.
    ``use_tlas`` (None = env tier) selects the two-level kernel variant;
    ``quant`` (None = the ``TRC_BVH_QUANT`` tier) the node format.
    """
    bvh = mesh.bvh
    instances = mesh.instances
    return _trace_fused_mesh(
        origins, directions,
        scene.centers, scene.radii, scene.albedo, scene.emission,
        scene.sun_direction, scene.sun_color, scene.sky_horizon,
        scene.sky_zenith, scene.plane_albedo_a, scene.plane_albedo_b,
        seed,
        instances.rotation, instances.translation, instances.scale,
        instances.albedo,
        bvh.v0, bvh.e1, bvh.e2, bvh.normal,
        bvh.bounds_min, bvh.bounds_max, bvh.skip, bvh.first, bvh.count,
        bvh.octant,
        max_bounces=max_bounces, interpret=_interpret(),
        use_tlas=use_tlas_for(instances.translation.shape[0], use_tlas),
        tlas_leaf=tlas_leaf_size(), tlas_block=tlas_block_r(),
        quant=bvh_quant_mode() if quant is None else int(quant),
    )


def intersect_instances_pallas(bvh, instances, origins, directions, init_t=None):
    """All-instance nearest hit in ONE kernel launch.

    ``init_t`` seeds the per-lane best-t (e.g. the same bounce's
    sphere/plane hit), culling instance walks that cannot beat it.
    Returns (t [R], triangle_index [R], instance_index [R]).
    """
    if init_t is None:
        init_t = jnp.full((origins.shape[0],), INF, jnp.float32)
    # Front-to-back instance order (distance from the mean live ray
    # origin): near instances set small best_t early, so the per-lane
    # ``wnear < best_t`` top-level cull rejects most far instances before
    # their walks start. Pure data reordering — results are order-
    # invariant — computed per call in XLA (the transforms are traced
    # values under jit, e.g. physics animation).
    # Dead lanes arrive as guaranteed-miss rays parked at 1e7 (integrator)
    # and must not drag the anchor off the scene.
    valid = (jnp.abs(origins) < 1e6).all(axis=1)
    anchor = jnp.sum(
        jnp.where(valid[:, None], origins, 0.0), axis=0
    ) / jnp.maximum(jnp.sum(valid), 1)
    near_first = jnp.argsort(
        jnp.sum((instances.translation - anchor[None, :]) ** 2, axis=1)
    )
    rotation = instances.rotation[near_first]
    translation = instances.translation[near_first]
    scale = instances.scale[near_first]
    # Per-block candidate ids index the SAME permuted order the kernel
    # sweeps (the table here is a [K, 22] recompute — trivial next to the
    # walk).
    table = _instance_table(
        rotation, translation, scale, bvh.bounds_min, bvh.bounds_max
    )
    block_candidate = _block_candidates(
        origins, directions, table[:, 13:16], table[:, 16:19]
    )
    t, tri, inst = _bvh_nearest_instanced(
        origins, directions, init_t, block_candidate,
        rotation, translation, scale,
        bvh.v0, bvh.e1, bvh.e2, bvh.bounds_min, bvh.bounds_max,
        bvh.skip, bvh.first, bvh.count,
        interpret=_interpret(),
    )
    return t, tri, near_first[inst]


def occluded_instances_pallas(bvh, instances, origins, directions, already):
    """All-instance any-hit in ONE kernel launch."""
    return _bvh_anyhit_instanced(
        origins, directions, already,
        instances.rotation, instances.translation, instances.scale,
        bvh.v0, bvh.e1, bvh.e2, bvh.bounds_min, bvh.bounds_max,
        bvh.skip, bvh.first, bvh.count,
        interpret=_interpret(),
    )


# ---------------------------------------------------------------------------
# Device-resident ray-pool (render/raypool.py) kernel plumbing.
#
# The pool driver runs the whole multi-frame batch inside ONE jitted
# lax.while_loop, so these wrappers are NOT jitted themselves: operand prep
# that is loop-invariant (the stacked multi-frame scene) is hoisted into
# PoolSphereOperands / PoolMeshOperands built once before the loop, and the
# per-iteration bounce call only transposes the pool state and launches the
# pool_io kernel. Pool width must be a multiple of the kernel block — the
# driver rounds up, so no per-call ray padding exists on this path.


class PoolSphereOperands(NamedTuple):
    """Loop-invariant kernel operands for a stacked multi-frame sphere
    scene (frames on a per-sphere ``fid`` column; padded slots fid=-1)."""

    c_t: jnp.ndarray  # [3, Np]
    r2: jnp.ndarray  # [Np, 1]
    csq: jnp.ndarray  # [Np, 1]
    rad: jnp.ndarray  # [Np, 1]
    albedo_t: jnp.ndarray  # [3, Np]
    emission_t: jnp.ndarray  # [3, Np]
    dc_sun: jnp.ndarray  # [Np, 1]
    sfid: jnp.ndarray  # [Np, 1] float32 frame ids (-1 = padding)
    params: jnp.ndarray  # [8, 3]


def pool_sphere_operands(
    centers, radii, albedo, emission, sphere_fid,
    sun_direction, sun_color, sky_horizon, sky_zenith,
    plane_albedo_a, plane_albedo_b,
) -> PoolSphereOperands:
    """Stack-prep for the pool sphere kernel. ``centers``/... are the
    multi-frame concatenation [F*N, ...]; ``sphere_fid`` [F*N] int."""
    n = centers.shape[0]
    padded_n = -(-n // _SUBLANE) * _SUBLANE
    pad = padded_n - n
    c_t = jnp.pad(centers, ((0, pad), (0, 0))).T
    radii_p = jnp.pad(radii, (0, pad))
    albedo_t = jnp.pad(albedo, ((0, pad), (0, 0))).T
    emission_t = jnp.pad(emission, ((0, pad), (0, 0))).T
    sfid = jnp.pad(
        sphere_fid.astype(jnp.float32), (0, pad), constant_values=-1.0
    )[:, None]
    params = jnp.zeros((8, 3), jnp.float32)
    params = params.at[0].set(sun_direction)
    params = params.at[1].set(sun_color)
    params = params.at[2].set(sky_horizon)
    params = params.at[3].set(sky_zenith)
    params = params.at[4].set(plane_albedo_a)
    params = params.at[5].set(plane_albedo_b)
    return PoolSphereOperands(
        c_t=c_t,
        r2=(radii_p * radii_p)[:, None],
        csq=jnp.sum(c_t * c_t, axis=0)[:, None],
        rad=radii_p[:, None],
        albedo_t=albedo_t,
        emission_t=emission_t,
        dc_sun=(c_t.T @ sun_direction)[:, None],
        sfid=sfid,
        params=params,
    )


class PoolMeshOperands(NamedTuple):
    """PoolSphereOperands plus the shared BVH and the stacked (multi-
    frame) instance transforms; ``ifid`` [F*K] marks each instance's
    frame. ``sun_direction`` rides along for the kernel's SMEM scalars."""

    spheres: PoolSphereOperands
    sun_direction: jnp.ndarray  # [3]
    # FID-MAJOR stacking contract: frame f's instances occupy rows
    # [f*K, (f+1)*K) — the kernel's per-block frame-window sweep indexes
    # the table by that arithmetic.
    rotation: jnp.ndarray  # [F*K, 3, 3]
    translation: jnp.ndarray  # [F*K, 3]
    scale: jnp.ndarray  # [F*K]
    inst_albedo: jnp.ndarray  # [F*K, 3]
    ifid: jnp.ndarray  # [F*K] int32
    k_per_frame: int  # K (static Python int; ops are closed over, not traced)
    v0: jnp.ndarray
    e1: jnp.ndarray
    e2: jnp.ndarray
    normal: jnp.ndarray
    bounds_min: jnp.ndarray
    bounds_max: jnp.ndarray
    skip: jnp.ndarray
    first: jnp.ndarray
    count: jnp.ndarray
    octant: object = None  # mesh.OctantTables | None (sah builds)


def pool_instance_aabbs(ops: PoolMeshOperands):
    """World AABBs (lo, hi) of the stacked instances — the broadphase
    input for the pool's coherence-sort candidate key."""
    table = _instance_table(
        ops.rotation, ops.translation, ops.scale,
        ops.bounds_min, ops.bounds_max,
    )
    return table[:, 13:16], table[:, 16:19]


def pool_sphere_bounce(
    ops: PoolSphereOperands, origins, directions, throughput, alive,
    lane, fid, seed_row, bounce_row, live_count, *, total_bounces: int,
):
    """One pool bounce over a sphere-only stacked scene.

    Pool width must be a multiple of SPHERE_BOUNCE_BLOCK_R. Returns
    (contribution [P, 3], origins, directions, throughput, alive).
    """
    rays = origins.shape[0]
    block = SPHERE_BOUNCE_BLOCK_R
    if rays % block:
        raise ValueError(f"pool width {rays} not a multiple of {block}")
    padded_n = ops.c_t.shape[1]
    o_t = origins.T
    d_t = directions.T
    thr_t = throughput.T
    alive_t = alive.astype(jnp.float32)[None, :]
    lane_t = lane.astype(jnp.int32)[None, :]
    seed_t = seed_row.astype(jnp.int32)[None, :]
    bounce_t = bounce_row.astype(jnp.int32)[None, :]
    fid_t = fid.astype(jnp.float32)[None, :]
    live_arr = jnp.asarray(live_count, jnp.int32).reshape(1, 1)

    grid = (rays // block,)
    whole = lambda i: (0, 0)  # noqa: E731
    ray_block = pl.BlockSpec(
        (3, block), lambda i: (0, i), memory_space=pltpu.VMEM
    )
    row_block = pl.BlockSpec(
        (1, block), lambda i: (0, i), memory_space=pltpu.VMEM
    )
    contrib, o2, d2, thr2, alive2 = pl.pallas_call(
        _trace_kernel_factory(total_bounces, padded_n, pool_io=True),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1), whole, memory_space=pltpu.SMEM),
            ray_block,
            ray_block,
            ray_block,
            row_block,
            row_block,
            row_block,
            row_block,
            row_block,
            pl.BlockSpec((3, padded_n), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((padded_n, 1), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((padded_n, 1), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((padded_n, 1), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((3, padded_n), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((3, padded_n), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((padded_n, 1), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((padded_n, 1), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((8, 3), whole, memory_space=pltpu.VMEM),
        ],
        out_specs=[ray_block, ray_block, ray_block, ray_block, row_block],
        out_shape=[
            jax.ShapeDtypeStruct((3, rays), jnp.float32),
            jax.ShapeDtypeStruct((3, rays), jnp.float32),
            jax.ShapeDtypeStruct((3, rays), jnp.float32),
            jax.ShapeDtypeStruct((3, rays), jnp.float32),
            jax.ShapeDtypeStruct((1, rays), jnp.float32),
        ],
        interpret=_interpret(),
    )(live_arr, o_t, d_t, thr_t, alive_t, lane_t, seed_t, bounce_t, fid_t,
      ops.c_t, ops.r2, ops.csq, ops.rad, ops.albedo_t, ops.emission_t,
      ops.dc_sun, ops.sfid, ops.params)
    return contrib.T, o2.T, d2.T, thr2.T, alive2[0] > 0.5


def pool_mesh_bounce(
    ops: PoolMeshOperands, origins, directions, throughput, alive,
    lane, fid, seed_row, bounce_row, live_count, *, total_bounces: int,
    use_tlas: bool = False, tlas_leaf: int = 4,
    tlas_block: int | None = None, quant: int = 0,
):
    """One pool bounce over a stacked multi-frame mesh scene.

    Pool width must be a multiple of the active ray block (the TLAS
    variant packets at the narrower tlas_block_r; every tlas_block_r
    divides BVH_BLOCK_R, so a BVH_BLOCK_R-rounded pool satisfies both).
    On the flat variant the front-to-back instance ordering is
    recomputed per call (ray origins move every iteration); the TLAS
    variant slot-orders each frame's segment by Morton code instead
    (ray-independent) and walks one per-frame TLAS window per block.
    Results are instance-order invariant either way, as in
    _mesh_bounce_io. Returns (contribution, origins, directions,
    throughput, alive, key-or-None).
    """
    from tpu_render_cluster.render.mesh import LEAF_SIZE

    if tlas_block is None:
        tlas_block = tlas_block_r()  # untraced callers only
    block = tlas_block if use_tlas else BVH_BLOCK_R
    rays = origins.shape[0]
    if rays % block:
        raise ValueError(
            f"pool width {rays} not a multiple of {block}"
        )
    sp = ops.spheres
    padded_n = sp.c_t.shape[1]
    o_t = origins.T
    d_t = directions.T
    thr_t = throughput.T
    alive_t = alive.astype(jnp.float32)[None, :]
    lane_t = lane.astype(jnp.int32)[None, :]
    seed_t = seed_row.astype(jnp.int32)[None, :]
    bounce_t = bounce_row.astype(jnp.int32)[None, :]
    fid_t = fid.astype(jnp.float32)[None, :]
    live_arr = jnp.asarray(live_count, jnp.int32).reshape(1, 1)
    # Per-block frame-id windows: the kernel sweeps only the table's
    # contiguous [fid_lo*K, (fid_hi+1)*K) slice for each block
    # (conservative: computed over every lane incl. the stale dead tail).
    fid_blocks = fid.astype(jnp.int32).reshape(rays // block, block)
    fid_lo = fid_blocks.min(axis=1)[None, :]  # [1, n_blocks]
    fid_hi = fid_blocks.max(axis=1)[None, :]

    k_per_frame = ops.k_per_frame
    n_frames = ops.rotation.shape[0] // k_per_frame
    if use_tlas:
        # Morton slot order WITHIN each frame's segment (stacking stays
        # fid-major — the kernel windows on frame f owning rows
        # [f*K, (f+1)*K)), plus one per-frame TLAS node window stacked
        # the same way: frame f's nodes are rows [f*M, (f+1)*M) with
        # skip links and leaf starts offset into the global node/slot
        # index spaces.
        from tpu_render_cluster.render.mesh import (
            cached_tlas_topology,
            instance_morton_order,
            tlas_node_bounds,
        )

        lo_w, hi_w = pool_instance_aabbs(ops)  # [F*K, 3]
        lo_f = lo_w.reshape(n_frames, k_per_frame, 3)
        hi_f = hi_w.reshape(n_frames, k_per_frame, 3)
        within = jax.vmap(instance_morton_order)(lo_f, hi_f)  # [F, K]
        near_first = (
            within
            + (jnp.arange(n_frames, dtype=within.dtype) * k_per_frame)[
                :, None
            ]
        ).reshape(-1)
        topology = cached_tlas_topology(k_per_frame, tlas_leaf)
        m = int(topology.skip.shape[0])
        slo = lo_w[near_first].reshape(n_frames, k_per_frame, 3)
        shi = hi_w[near_first].reshape(n_frames, k_per_frame, 3)
        node_lo, node_hi = jax.vmap(
            lambda lo, hi: tlas_node_bounds(topology, lo, hi)
        )(slo, shi)
        node_offset = jnp.arange(n_frames, dtype=jnp.int32)[:, None] * m
        slot_offset = (
            jnp.arange(n_frames, dtype=jnp.int32)[:, None] * k_per_frame
        )
        key_lo, key_inv = mesh_key_bounds(lo_w, hi_w)
        tlas_nodes = n_frames * m
        tlas_per_frame = m
        quant = resolve_bvh_quant(
            quant,
            (ops.skip.shape[0], ops.v0.shape[0] // LEAF_SIZE, LEAF_SIZE),
            (tlas_nodes, ops.rotation.shape[0], tlas_leaf),
        )
        # The stacked per-frame node windows quantize against ONE grid
        # (the union over every frame's instance AABBs): skip/leaf-start
        # links carry their frame offsets INSIDE the packed meta words.
        tlas_operands, tlas_specs = _node_table_operands(
            node_lo.reshape(-1, 3),
            node_hi.reshape(-1, 3),
            (jnp.asarray(topology.skip)[None, :] + node_offset).reshape(-1),
            (jnp.asarray(topology.first)[None, :] + slot_offset).reshape(
                -1
            ),
            jnp.tile(jnp.asarray(topology.count), n_frames),
            quant=quant, first_unit=1,
        )
        extra_operands = (
            *tlas_operands, jnp.concatenate([key_lo, key_inv]),
        )
    else:
        # Front-to-back instance order WITHIN each frame's segment, from
        # the mean live origin (dead lanes parked far away must not drag
        # the anchor): near instances seed tight best-t early within
        # each frame. Results are instance-order invariant, as in
        # _mesh_bounce_io.
        valid = (jnp.abs(origins) < 1e6).all(axis=1) & alive
        anchor = jnp.sum(
            jnp.where(valid[:, None], origins, 0.0), axis=0
        ) / jnp.maximum(jnp.sum(valid), 1)
        dist2 = jnp.sum(
            (ops.translation - anchor[None, :]) ** 2, axis=1
        ).reshape(n_frames, k_per_frame)
        within = jnp.argsort(dist2, axis=1)  # [F, K]
        near_first = (
            within
            + (jnp.arange(n_frames, dtype=within.dtype) * k_per_frame)[
                :, None
            ]
        ).reshape(-1)
        quant = resolve_bvh_quant(
            quant,
            (ops.skip.shape[0], ops.v0.shape[0] // LEAF_SIZE, LEAF_SIZE),
        )
        tlas_specs = []
        extra_operands = ()
        tlas_nodes = 0
        tlas_per_frame = 0
    inst_table = _instance_table(
        ops.rotation[near_first], ops.translation[near_first],
        ops.scale[near_first],
        ops.bounds_min, ops.bounds_max, ops.inst_albedo[near_first],
    )
    inst_table = jnp.concatenate(
        [inst_table, ops.ifid[near_first].astype(jnp.float32)[:, None]],
        axis=1,
    )  # [F*K, 23]: column 22 is the instance's frame id
    n_nodes = ops.skip.shape[0]
    k_count = ops.rotation.shape[0]

    grid = (rays // block,)
    whole = lambda i: (0, 0)  # noqa: E731
    flat = lambda i: (0,)  # noqa: E731
    ray_block = pl.BlockSpec(
        (3, block), lambda i: (0, i), memory_space=pltpu.VMEM
    )
    row_block = pl.BlockSpec(
        (1, block), lambda i: (0, i), memory_space=pltpu.VMEM
    )
    blas_arrays = _blas_node_arrays(
        ops.bounds_min, ops.bounds_max, ops.skip, ops.first, ops.count,
        ops.octant,
    )
    ordered = blas_arrays[5]
    blas_operands, blas_specs = _node_table_operands(
        *blas_arrays[:5], quant=quant, first_unit=LEAF_SIZE,
    )
    extra_specs = (
        tlas_specs + [pl.BlockSpec((6,), flat, memory_space=pltpu.SMEM)]
        if use_tlas
        else []
    )
    key_out_specs = [row_block] if use_tlas else []
    key_out_shapes = (
        [jax.ShapeDtypeStruct((1, rays), jnp.int32)] if use_tlas else []
    )
    results = pl.pallas_call(
        _mesh_trace_kernel_factory(
            total_bounces, padded_n, n_nodes, LEAF_SIZE, k_count,
            pool_io=True, k_per_frame=k_per_frame,
            use_tlas=use_tlas, tlas_nodes=tlas_nodes,
            tlas_per_frame=tlas_per_frame, quant=quant, ordered=ordered,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1), whole, memory_space=pltpu.SMEM),
            ray_block,
            ray_block,
            ray_block,
            row_block,
            row_block,
            row_block,
            row_block,
            row_block,
            pl.BlockSpec((1, 1), lambda i: (0, i), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda i: (0, i), memory_space=pltpu.SMEM),
            pl.BlockSpec((3, padded_n), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((padded_n, 1), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((padded_n, 1), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((padded_n, 1), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((3, padded_n), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((3, padded_n), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((padded_n, 1), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((padded_n, 1), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((8, 3), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((3,), flat, memory_space=pltpu.SMEM),
            pl.BlockSpec(inst_table.shape, whole, memory_space=pltpu.SMEM),
            pl.BlockSpec(ops.v0.shape, whole, memory_space=pltpu.VMEM),
            pl.BlockSpec(ops.e1.shape, whole, memory_space=pltpu.VMEM),
            pl.BlockSpec(ops.e2.shape, whole, memory_space=pltpu.VMEM),
            pl.BlockSpec(ops.normal.shape, whole, memory_space=pltpu.VMEM),
        ] + blas_specs + extra_specs,
        out_specs=[ray_block, ray_block, ray_block, ray_block, row_block]
        + key_out_specs,
        out_shape=[
            jax.ShapeDtypeStruct((3, rays), jnp.float32),
            jax.ShapeDtypeStruct((3, rays), jnp.float32),
            jax.ShapeDtypeStruct((3, rays), jnp.float32),
            jax.ShapeDtypeStruct((3, rays), jnp.float32),
            jax.ShapeDtypeStruct((1, rays), jnp.float32),
        ] + key_out_shapes,
        interpret=_interpret(),
    )(live_arr, o_t, d_t, thr_t, alive_t, lane_t, seed_t, bounce_t, fid_t,
      fid_lo, fid_hi,
      sp.c_t, sp.r2, sp.csq, sp.rad, sp.albedo_t, sp.emission_t,
      sp.dc_sun, sp.sfid, sp.params, ops.sun_direction, inst_table,
      ops.v0, ops.e1, ops.e2, ops.normal, *blas_operands,
      *extra_operands)
    contrib, o2, d2, thr2, alive2 = results[:5]
    key2 = results[5][0] if use_tlas else None
    return contrib.T, o2.T, d2.T, thr2.T, alive2[0] > 0.5, key2
