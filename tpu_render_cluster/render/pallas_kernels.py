"""Pallas TPU kernel for the render engine's hot op: nearest-hit intersection.

The path tracer spends its time in the rays x spheres intersection
(reference analog: the per-frame render loop inside Blender that
worker/src/rendering/runner/mod.rs shells out to; here the render engine is
TPU-native so the hot loop is ours to own). The XLA version in
``geometry.intersect_spheres`` materializes several [R, N] intermediates
between HBM-level fusions; this kernel fuses quadratic solve, validity
masking, and the min/argmin reduction into one VMEM-resident pass per ray
block.

Layout choices (see /opt/skills/guides/pallas_guide.md):
- rays ride the *lane* axis (128-wide) as [3, BLOCK_R] blocks; the sphere
  axis is the sublane axis, so the nearest-hit reduction is a sublane
  reduction producing [1, BLOCK_R];
- sphere data ([3, N] centers, [N, 1] radius^2 / |c|^2) is small enough to
  sit whole in VMEM for every grid step;
- the two contractions (d.c and o.c) are K=3 dot_generals on the MXU with
  ``preferred_element_type=float32``.

On non-TPU backends the kernel runs in interpret mode, so the same code
path is exercised by CPU tests.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Plain Python floats: a jnp constant would be captured as a traced value,
# which pallas_call rejects.
INF = 1e30
EPS = 1e-3

BLOCK_R = 1024  # rays per grid step (8 f32 lane-tiles)
_SUBLANE = 8  # f32 sublane tile; sphere count is padded to a multiple


def pallas_enabled() -> bool:
    """Whether intersect dispatches to the Pallas kernel.

    Default: only on a real TPU backend (interpret mode is a debugging
    path, much slower than XLA on CPU). ``TRC_PALLAS=1`` forces it on
    anywhere (tests use this); ``TRC_PALLAS=0`` disables it.

    Read at *trace* time: jitted callers bake the decision into their
    compiled executable, so flipping the env var mid-process has no effect
    on already-compiled functions (jax.clear_caches() to re-trace).
    """
    value = os.environ.get("TRC_PALLAS")
    if value is None:
        return jax.default_backend() == "tpu"
    return value not in ("0", "false", "off")


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _nearest_hit_kernel(o_ref, d_ref, c_ref, r2_ref, csq_ref, t_ref, idx_ref):
    """One ray block vs all spheres; writes min-t and argmin index."""
    o = o_ref[:, :]  # [3, BR]
    d = d_ref[:, :]  # [3, BR]
    c = c_ref[:, :]  # [3, N]
    contract_first = (((0,), (0,)), ((), ()))
    # [N, BR] contractions on the MXU.
    dc = jax.lax.dot_general(c, d, contract_first, preferred_element_type=jnp.float32)
    oc = jax.lax.dot_general(c, o, contract_first, preferred_element_type=jnp.float32)
    od = jnp.sum(o * d, axis=0, keepdims=True)  # [1, BR]
    o_sq = jnp.sum(o * o, axis=0, keepdims=True)  # [1, BR]

    r2 = r2_ref[:, :]  # [N, 1]
    oc_dot_d = dc - od  # d . (c - o)
    oc_sq = o_sq - 2.0 * oc + csq_ref[:, :]  # |o - c|^2
    disc = oc_dot_d * oc_dot_d - (oc_sq - r2)
    valid = (disc > 0.0) & (r2 > 0.0)
    sqrt_disc = jnp.sqrt(jnp.maximum(disc, 0.0))
    t0 = oc_dot_d - sqrt_disc
    t1 = oc_dot_d + sqrt_disc
    t = jnp.where(t0 > EPS, t0, jnp.where(t1 > EPS, t1, INF))
    t = jnp.where(valid, t, INF)  # [N, BR]

    n = t.shape[0]
    t_min = jnp.min(t, axis=0, keepdims=True)  # [1, BR]
    lanes = jax.lax.broadcasted_iota(jnp.int32, t.shape, 0)
    # First index attaining the min (matches jnp.argmin tie-breaking).
    idx = jnp.min(jnp.where(t == t_min, lanes, n), axis=0, keepdims=True)
    t_ref[:, :] = t_min
    idx_ref[:, :] = jnp.minimum(idx, n - 1)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _nearest_hit(origins, directions, centers, radii, *, interpret: bool):
    rays = origins.shape[0]
    padded_rays = -(-rays // BLOCK_R) * BLOCK_R
    ray_pad = padded_rays - rays
    o_t = jnp.pad(origins, ((0, ray_pad), (0, 0))).T  # [3, Rp]
    d_t = jnp.pad(directions, ((0, ray_pad), (0, 0))).T  # [3, Rp]

    n = centers.shape[0]
    padded_n = -(-n // _SUBLANE) * _SUBLANE
    sphere_pad = padded_n - n
    c_t = jnp.pad(centers, ((0, sphere_pad), (0, 0))).T  # [3, Np]
    radii = jnp.pad(radii, (0, sphere_pad))
    r2 = (radii * radii)[:, None]  # [Np, 1]
    csq = jnp.sum(c_t * c_t, axis=0)[:, None]  # [Np, 1]

    grid = (padded_rays // BLOCK_R,)
    t, idx = pl.pallas_call(
        _nearest_hit_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((3, BLOCK_R), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((3, BLOCK_R), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((3, padded_n), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((padded_n, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((padded_n, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, BLOCK_R), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, BLOCK_R), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, padded_rays), jnp.float32),
            jax.ShapeDtypeStruct((1, padded_rays), jnp.int32),
        ],
        interpret=interpret,
    )(o_t, d_t, c_t, r2, csq)
    return t[0, :rays], idx[0, :rays]


def intersect_spheres_pallas(scene, origins, directions):
    """Drop-in Pallas replacement for ``geometry.intersect_spheres``.

    Returns (t [R] float32 with INF misses, index [R] int32).
    """
    # Padded ray slots (zero origin/direction) produce harmless garbage that
    # the wrapper slices off; padded sphere slots have r2 == 0 -> never hit.
    t, idx = _nearest_hit(
        origins, directions, scene.centers, scene.radii, interpret=_interpret()
    )
    # Padded sphere indices can only appear for all-miss rays (t == INF);
    # clamp into range like the jnp argmin would.
    return t, jnp.minimum(idx, scene.centers.shape[0] - 1)
