"""Pinhole camera: frame-animated orbit, pixel-grid ray generation."""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp


class Camera(NamedTuple):
    origin: jnp.ndarray  # [3]
    forward: jnp.ndarray  # [3] unit
    right: jnp.ndarray  # [3] unit
    up: jnp.ndarray  # [3] unit
    tan_half_fov: jnp.ndarray  # scalar


def _normalize(v):
    return v / jnp.linalg.norm(v)


def look_at_camera(origin, target, *, fov_degrees: float = 45.0) -> Camera:
    origin = jnp.asarray(origin, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    forward = _normalize(target - origin)
    world_up = jnp.array([0.0, 1.0, 0.0], jnp.float32)
    right = _normalize(jnp.cross(forward, world_up))
    up = jnp.cross(right, forward)
    tan_half_fov = jnp.tan(jnp.deg2rad(fov_degrees) / 2.0).astype(jnp.float32)
    return Camera(origin, forward, right, up, tan_half_fov)


def scene_camera(scene_name: str, frame) -> Camera:
    """Default camera per scene family; orbits slowly for animation scenes."""
    frame = jnp.asarray(frame, jnp.float32)
    if scene_name == "01_simple-animation":
        angle = frame * (2.0 * jnp.pi / 600.0)
        origin = jnp.stack(
            [9.0 * jnp.cos(angle), 4.5, 9.0 * jnp.sin(angle)]
        )
        return look_at_camera(origin, [0.0, 0.8, 0.0])
    if scene_name.startswith(("02_physics", "03_physics-2")):
        return look_at_camera([10.0, 6.0, 10.0], [0.0, 1.0, 0.0])
    # 04_very-simple: fixed three-quarter view of the grid.
    return look_at_camera([8.0, 6.5, 8.0], [0.0, 0.4, 0.0])


def camera_rays(
    camera: Camera,
    width: int,
    height: int,
    *,
    y0: int | jnp.ndarray = 0,
    x0: int | jnp.ndarray = 0,
    tile_height: int | None = None,
    tile_width: int | None = None,
    jitter: jnp.ndarray | None = None,
):
    """Ray origins/directions for a pixel tile.

    Returns (origins [h*w, 3], directions [h*w, 3]). ``jitter`` is an
    optional [h*w, 2] in [0,1) for stratified anti-aliasing.
    """
    h = tile_height if tile_height is not None else height
    w = tile_width if tile_width is not None else width
    ys = jnp.arange(h, dtype=jnp.float32) + jnp.asarray(y0, jnp.float32)
    xs = jnp.arange(w, dtype=jnp.float32) + jnp.asarray(x0, jnp.float32)
    py, px = jnp.meshgrid(ys, xs, indexing="ij")
    px = px.reshape(-1)
    py = py.reshape(-1)
    if jitter is None:
        off_x = 0.5
        off_y = 0.5
    else:
        off_x = jitter[:, 0]
        off_y = jitter[:, 1]
    aspect = width / height
    ndc_x = ((px + off_x) / width * 2.0 - 1.0) * aspect * camera.tan_half_fov
    ndc_y = (1.0 - (py + off_y) / height * 2.0) * camera.tan_half_fov
    directions = (
        camera.forward[None, :]
        + ndc_x[:, None] * camera.right[None, :]
        + ndc_y[:, None] * camera.up[None, :]
    )
    directions = directions / jnp.linalg.norm(directions, axis=-1, keepdims=True)
    origins = jnp.broadcast_to(camera.origin, directions.shape)
    return origins, directions
