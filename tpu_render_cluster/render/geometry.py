"""Ray-scene intersection.

The hot op is a [rays x spheres] batch intersection whose inner products are
matmul-shaped (``o @ centers^T``, ``d @ centers^T``) so XLA tiles them onto
the MXU. Padded sphere slots carry radius 0 and never produce hits. A Pallas
variant of the same kernel lives in pallas_kernels.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpu_render_cluster.render.scene import Scene

# Plain Python float (not a jnp scalar): a module-level device constant
# would be created during whatever trace first imports the module and leak
# that trace's tracer into every later caller.
INF = 1e30
EPS = 1e-3


def _ray_barrier(origins, directions):
    """TPU-only fusion barrier around the ray inputs.

    Keeps XLA from fusing ray-producing broadcasts/iotas into the matmuls
    below: the v5e TpuPriorityFusionQueue cost model SIGILLs on that
    producer pattern (libtpu crash observed 2026-07; also materializes the
    rays once instead of recomputing them in all three contractions). On
    non-TPU backends the barrier buys nothing and older JAX releases have
    no batching rule for it (it breaks under the pre-0.5 shard_map), so
    it is skipped.
    """
    if jax.default_backend() != "tpu":
        return origins, directions
    return jax.lax.optimization_barrier((origins, directions))


def intersect_spheres(scene: Scene, origins, directions):
    """Nearest sphere hit per ray.

    Args:
      origins, directions: [R, 3] float32 (directions unit).
    Returns:
      (t [R], index [R] int32) — t = INF when no hit.
    """
    from tpu_render_cluster.render import pallas_kernels

    if pallas_kernels.pallas_enabled():
        return pallas_kernels.intersect_spheres_pallas(scene, origins, directions)
    origins, directions = _ray_barrier(origins, directions)
    oc_dot_d = directions @ scene.centers.T - jnp.sum(
        directions * origins, axis=-1, keepdims=True
    )  # [R, N] = d . (c - o)
    # |o - c|^2 = |o|^2 - 2 o.c + |c|^2
    o_sq = jnp.sum(origins * origins, axis=-1, keepdims=True)
    c_sq = jnp.sum(scene.centers * scene.centers, axis=-1)[None, :]
    oc_sq = o_sq - 2.0 * (origins @ scene.centers.T) + c_sq
    disc = oc_dot_d**2 - (oc_sq - scene.radii[None, :] ** 2)
    valid = (disc > 0.0) & (scene.radii[None, :] > 0.0)
    sqrt_disc = jnp.sqrt(jnp.maximum(disc, 0.0))
    t0 = oc_dot_d - sqrt_disc
    t1 = oc_dot_d + sqrt_disc
    t = jnp.where(t0 > EPS, t0, jnp.where(t1 > EPS, t1, INF))
    t = jnp.where(valid, t, INF)
    best = jnp.argmin(t, axis=-1).astype(jnp.int32)
    t_best = jnp.take_along_axis(t, best[:, None], axis=-1)[:, 0]
    return t_best, best


def intersect_plane(origins, directions):
    """Ground plane y=0; returns t (INF when parallel or behind)."""
    denom = directions[:, 1]
    t = -origins[:, 1] / jnp.where(jnp.abs(denom) < 1e-8, 1e-8, denom)
    return jnp.where((t > EPS) & (jnp.abs(denom) >= 1e-8), t, INF)


def intersect_scene(scene: Scene, origins, directions):
    """Nearest hit among spheres and the ground plane.

    Returns (t [R], sphere_index [R], is_plane [R] bool).
    """
    t_sphere, sphere_index = intersect_spheres(scene, origins, directions)
    t_plane = intersect_plane(origins, directions)
    is_plane = t_plane < t_sphere
    t = jnp.minimum(t_sphere, t_plane)
    return t, sphere_index, is_plane


def occluded(scene: Scene, origins, directions, max_t) -> jnp.ndarray:
    """Boolean shadow query: any sphere hit with t < max_t (plane excluded —
    the sun is always above the plane)."""
    t_sphere, _ = intersect_spheres(scene, origins, directions)
    return t_sphere < max_t


def occluded_sun(scene: Scene, origins, directions) -> jnp.ndarray:
    """Unbounded any-hit shadow query (the sun is a delta light at infinity).

    Cheaper than ``occluded``: no nearest-hit ordering or argmin is needed,
    just "does any sphere lie in front" — on TPU this runs a dedicated
    Pallas any-hit kernel with a single OR-reduction over spheres.
    """
    from tpu_render_cluster.render import pallas_kernels

    if pallas_kernels.pallas_enabled():
        return pallas_kernels.occluded_pallas(scene, origins, directions)
    origins, directions = _ray_barrier(origins, directions)
    oc_dot_d = directions @ scene.centers.T - jnp.sum(
        directions * origins, axis=-1, keepdims=True
    )
    o_sq = jnp.sum(origins * origins, axis=-1, keepdims=True)
    c_sq = jnp.sum(scene.centers * scene.centers, axis=-1)[None, :]
    oc_sq = o_sq - 2.0 * (origins @ scene.centers.T) + c_sq
    disc = oc_dot_d**2 - (oc_sq - scene.radii[None, :] ** 2)
    valid = (disc > 0.0) & (scene.radii[None, :] > 0.0)
    t1 = oc_dot_d + jnp.sqrt(jnp.maximum(disc, 0.0))
    return jnp.any(valid & (t1 > EPS), axis=-1)


def checker_albedo(scene: Scene, points) -> jnp.ndarray:
    """Checkerboard albedo for plane hit points [R, 3]."""
    checker = (
        jnp.floor(points[:, 0]).astype(jnp.int32)
        + jnp.floor(points[:, 2]).astype(jnp.int32)
    ) % 2
    return jnp.where(
        checker[:, None] == 0, scene.plane_albedo_a[None, :], scene.plane_albedo_b[None, :]
    )


def sky_color(scene: Scene, directions) -> jnp.ndarray:
    """Vertical-gradient sky with a visible sun disc."""
    blend = jnp.clip(directions[:, 1], 0.0, 1.0)[:, None]
    base = (1.0 - blend) * scene.sky_horizon[None, :] + blend * scene.sky_zenith[None, :]
    sun_cos = directions @ scene.sun_direction
    sun_disc = jnp.where(sun_cos > 0.9995, 40.0, 0.0)[:, None]
    return base + sun_disc * scene.sun_color[None, :] / 40.0 * 8.0
