"""Procedural scenes as static-shape structure-of-arrays.

Each scene family mirrors one of the reference's Blender job projects
(reference: blender-projects/{01_simple-animation,02_physics,03_physics-2,
04_very-simple}) in spirit: a ground plane, a set of spheres, a sun light,
and a sky. Scene arrays are pure functions of the frame index (animation
and physics are closed-form in time), so a batch of frames can be built
with ``jax.vmap(lambda f: build_scene(name, f))`` and rendered as one
device-resident batch — no host round-trips between frames.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class Scene(NamedTuple):
    """Structure-of-arrays scene with static shapes (pads with radius=0)."""

    centers: jnp.ndarray  # [N, 3] float32
    radii: jnp.ndarray  # [N] float32, 0 = unused slot
    albedo: jnp.ndarray  # [N, 3] float32
    emission: jnp.ndarray  # [N, 3] float32
    # Ground plane y=0 with a checkerboard albedo.
    plane_albedo_a: jnp.ndarray  # [3]
    plane_albedo_b: jnp.ndarray  # [3]
    # Sun (delta directional light).
    sun_direction: jnp.ndarray  # [3], unit, points TOWARD the sun
    sun_color: jnp.ndarray  # [3]
    # Sky gradient colors.
    sky_horizon: jnp.ndarray  # [3]
    sky_zenith: jnp.ndarray  # [3]


SCENE_NAMES = (
    "04_very-simple",
    "01_simple-animation",
    "02_physics-mesh",
    "02_physics",
    "03_physics-2-mesh",
    "03_physics-2",
)

_FPS = 24.0
_GRAVITY = 9.81


def _normalize(v):
    return v / jnp.linalg.norm(v)


def _default_lighting() -> dict:
    return dict(
        plane_albedo_a=jnp.array([0.85, 0.85, 0.85], jnp.float32),
        plane_albedo_b=jnp.array([0.25, 0.3, 0.35], jnp.float32),
        sun_direction=_normalize(jnp.array([0.4, 0.8, 0.3], jnp.float32)),
        sun_color=jnp.array([2.7, 2.5, 2.2], jnp.float32),
        sky_horizon=jnp.array([0.65, 0.75, 0.9], jnp.float32),
        sky_zenith=jnp.array([0.15, 0.3, 0.6], jnp.float32),
    )


def _pad_spheres(centers, radii, albedo, emission, size: int) -> tuple:
    n = centers.shape[0]
    if n > size:
        raise ValueError(f"Scene has {n} spheres, exceeds pad size {size}.")
    pad = size - n
    centers = jnp.concatenate([centers, jnp.zeros((pad, 3), jnp.float32)])
    radii = jnp.concatenate([radii, jnp.zeros((pad,), jnp.float32)])
    albedo = jnp.concatenate([albedo, jnp.zeros((pad, 3), jnp.float32)])
    emission = jnp.concatenate([emission, jnp.zeros((pad, 3), jnp.float32)])
    return centers, radii, albedo, emission


def _grid_colors(n: int) -> jnp.ndarray:
    """Deterministic pleasant albedos (golden-ratio hue walk)."""
    indices = jnp.arange(n, dtype=jnp.float32)
    hue = jnp.mod(indices * 0.61803398875, 1.0)
    # Cheap HSV->RGB with fixed s/v.
    h6 = hue * 6.0
    x = 1.0 - jnp.abs(jnp.mod(h6, 2.0) - 1.0)
    zeros = jnp.zeros_like(hue)
    ones = jnp.ones_like(hue)
    sector = jnp.floor(h6).astype(jnp.int32) % 6
    r = jnp.select([sector == 0, sector == 1, sector == 2, sector == 3, sector == 4], [ones, x, zeros, zeros, x], ones)
    g = jnp.select([sector == 0, sector == 1, sector == 2, sector == 3, sector == 4], [x, ones, ones, x, zeros], zeros)
    b = jnp.select([sector == 0, sector == 1, sector == 2, sector == 3, sector == 4], [zeros, zeros, x, ones, ones], x)
    rgb = jnp.stack([r, g, b], axis=-1)
    return 0.25 + 0.65 * rgb


def _very_simple(frame: jnp.ndarray, n_spheres: int = 64, pad: int = 64):
    """Static sphere grid (the 04_very-simple workhorse scene)."""
    side = int(np.ceil(np.sqrt(n_spheres)))
    index = jnp.arange(n_spheres)
    gx = (index % side).astype(jnp.float32) - (side - 1) / 2.0
    gz = (index // side).astype(jnp.float32) - (side - 1) / 2.0
    radius = jnp.full((n_spheres,), 0.45, jnp.float32)
    centers = jnp.stack([gx * 1.2, radius, gz * 1.2], axis=-1)
    albedo = _grid_colors(n_spheres)
    emission = jnp.zeros((n_spheres, 3), jnp.float32)
    # One emissive sphere so indirect light is visible.
    emission = emission.at[0].set(jnp.array([4.0, 3.6, 3.0]))
    return _pad_spheres(centers, radius, albedo, emission, pad)


def _simple_animation(frame: jnp.ndarray, n_spheres: int = 24, pad: int = 32):
    """Spheres orbiting a center column, phase-shifted per sphere."""
    t = frame / _FPS
    index = jnp.arange(n_spheres, dtype=jnp.float32)
    phase = index * (2.0 * jnp.pi / n_spheres)
    ring = 1.0 + (index % 3.0)
    angle = phase + t * (0.8 + 0.15 * (index % 3.0))
    y = 0.5 + 0.3 * jnp.sin(t * 2.0 + phase * 2.0) + 0.35 * (index % 3.0)
    centers = jnp.stack(
        [ring * 1.4 * jnp.cos(angle), y, ring * 1.4 * jnp.sin(angle)], axis=-1
    )
    radii = jnp.full((n_spheres,), 0.35, jnp.float32)
    albedo = _grid_colors(n_spheres)
    emission = jnp.zeros((n_spheres, 3), jnp.float32)
    emission = emission.at[0].set(jnp.array([5.0, 4.5, 3.5]))
    return _pad_spheres(centers, radii, albedo, emission, pad)


def _physics(frame: jnp.ndarray, n_spheres: int, pad: int, *, chaos: float):
    """Falling-and-bouncing spheres with closed-form ballistic motion.

    A cheap stand-in for the reference's baked rigid-body sims
    (blender-projects/02_physics, 03_physics-2): each sphere drops from a
    per-sphere height with elastic bounces (restitution 0.7), so position
    at any frame is computable without simulation state.
    """
    t = frame / _FPS
    index = jnp.arange(n_spheres, dtype=jnp.float32)
    # Deterministic pseudo-random spread from the index.
    u1 = jnp.mod(index * 0.7548776662, 1.0)
    u2 = jnp.mod(index * 0.5698402909, 1.0)
    u3 = jnp.mod(index * 0.3819660113, 1.0)
    radius = 0.25 + 0.15 * u3
    x = (u1 - 0.5) * 8.0 + chaos * 0.5 * jnp.sin(12.0 * u2)
    z = (u2 - 0.5) * 8.0 + chaos * 0.5 * jnp.cos(12.0 * u1)
    h0 = 3.0 + 5.0 * u3  # drop height
    drop_delay = u1 * 2.0 * chaos
    tau = jnp.maximum(t - drop_delay, 0.0)

    y = _ballistic_height(tau, h0) + radius
    centers = jnp.stack([x, y, z], axis=-1)
    albedo = _grid_colors(n_spheres)
    emission = jnp.zeros((n_spheres, 3), jnp.float32)
    return _pad_spheres(centers, radius, albedo, emission, pad)


def _ballistic_height(t, h0, *, restitution: float = 0.7):
    """Closed-form bounce height at time t for a drop from h0 (see _physics)."""
    e = restitution
    v0 = jnp.sqrt(2.0 * _GRAVITY * h0)
    t_fall = jnp.sqrt(2.0 * h0 / _GRAVITY)
    in_fall = t < t_fall
    fall_y = h0 - 0.5 * _GRAVITY * t**2
    s = t - t_fall
    denom = 2.0 * v0 / (_GRAVITY * (1.0 - e))
    ratio = jnp.clip(1.0 - s / denom, 1e-6, 1.0)
    k = jnp.clip(jnp.floor(jnp.log(ratio) / jnp.log(e)), 0.0, 40.0)
    elapsed = denom * (1.0 - e**k)
    local = s - elapsed
    vk = v0 * e**k
    bounce_y = jnp.maximum(vk * local - 0.5 * _GRAVITY * local**2, 0.0)
    settled = vk < 0.15
    return jnp.where(in_fall, fall_y, jnp.where(settled, 0.0, bounce_y))


def build_mesh_instances(name: str, frame):
    """Mesh instance transforms for mesh-backed scenes, else ``None``.

    02_physics-mesh: K tumbling boxes dropped ballistically (the mesh
    counterpart of the _physics sphere rain — reference analog:
    blender-projects/02_physics rigid bodies). Topology is static (one
    shared box BVH); only the rigid transforms depend on the frame, so the
    whole thing jits and vmaps over frames.
    """
    if name not in ("02_physics-mesh", "03_physics-2-mesh"):
        return None
    from tpu_render_cluster.render.mesh import MeshInstances, rotation_y

    frame = jnp.asarray(frame, jnp.float32)
    t = frame / _FPS
    # 03's variant: more, smaller icosphere instances (chaotic spread) —
    # the deeper 127-node BVH makes traversal depth matter.
    k = 48 if name == "03_physics-2-mesh" else 24
    index = jnp.arange(k, dtype=jnp.float32)
    u1 = jnp.mod(index * 0.7548776662, 1.0)
    u2 = jnp.mod(index * 0.5698402909, 1.0)
    u3 = jnp.mod(index * 0.3819660113, 1.0)
    if name == "03_physics-2-mesh":
        size = 0.45 + 0.35 * u3
        x = (u1 - 0.5) * 9.0 + 0.5 * jnp.sin(12.0 * u2)
        z = (u2 - 0.5) * 9.0 + 0.5 * jnp.cos(12.0 * u1)
        h0 = 2.0 + 5.0 * u3
        tau = jnp.maximum(t - u1 * 2.0, 0.0)
    else:
        size = 0.6 + 0.5 * u3
        x = (u1 - 0.5) * 7.0
        z = (u2 - 0.5) * 7.0
        h0 = 2.5 + 4.0 * u3
        tau = jnp.maximum(t - u1 * 1.5, 0.0)
    y = _ballistic_height(tau, h0) + size * 0.5
    rotation = rotation_y(tau * (0.6 + 2.0 * u2) + u1 * 6.28)
    translation = jnp.stack([x, y, z], axis=-1)
    albedo = _grid_colors(k)
    return MeshInstances(
        rotation=rotation, translation=translation, albedo=albedo, scale=size
    )


def obj_stage_scene(frame) -> Scene:
    """Minimal stage for user OBJ meshes (``render.cli --obj``): two accent
    spheres beside the turntable, default plane/sun/sky."""
    del frame  # static stage; the OBJ instance itself rotates per frame
    centers = jnp.array(
        [[2.6, 0.45, -1.4], [-2.4, 0.35, 1.6]], jnp.float32
    )
    radii = jnp.array([0.45, 0.35], jnp.float32)
    albedo = jnp.array([[0.8, 0.35, 0.3], [0.3, 0.45, 0.8]], jnp.float32)
    emission = jnp.zeros((2, 3), jnp.float32)
    padded = _pad_spheres(centers, radii, albedo, emission, 8)
    return Scene(*padded, **_default_lighting())


def mesh_kind_for_scene(name: str) -> str | None:
    """Which cached object-space BVH a mesh scene uses (None = no mesh)."""
    if name == "02_physics-mesh":
        return "box"
    if name == "03_physics-2-mesh":
        return "icosphere"
    return None


def build_scene(name: str, frame) -> Scene:
    """Build the scene arrays for one frame (jit/vmap friendly in ``frame``)."""
    frame = jnp.asarray(frame, jnp.float32)
    if name == "04_very-simple":
        spheres = _very_simple(frame)
    elif name == "01_simple-animation":
        spheres = _simple_animation(frame)
    elif name == "02_physics":
        spheres = _physics(frame, 48, 64, chaos=0.0)
    elif name == "02_physics-mesh":
        # A handful of spheres accompany the boxes (sky + plane + spheres
        # exercise every primitive in one scene); the boxes ride the mesh
        # path via build_mesh_instances.
        spheres = _physics(frame, 12, 16, chaos=0.0)
    elif name == "03_physics-2":
        spheres = _physics(frame, 96, 128, chaos=1.0)
    elif name == "03_physics-2-mesh":
        spheres = _physics(frame, 16, 16, chaos=1.0)
    else:
        raise ValueError(f"Unknown scene: {name!r} (have {SCENE_NAMES})")
    centers, radii, albedo, emission = spheres
    return Scene(centers, radii, albedo, emission, **_default_lighting())


def scene_for_job_name(job_name: str) -> str:
    """Map a job name to a scene family.

    Covers the reference TOML convention ("01-simple-animation_...",
    "04_very-simple_...") and this repo's generated grid labels
    ("01sa_...", "02ph_...", "03ph2_...", "04vs_..."): the two-digit
    project number prefix is unique across families.
    """
    # Exact family-name prefixes first, longest first, so
    # "02_physics-mesh_x" doesn't fall through to "02_physics".
    for name in sorted(SCENE_NAMES, key=len, reverse=True):
        if job_name.startswith(name):
            return name
    # Two-digit project prefixes map to the classic (non-mesh) families.
    for name in SCENE_NAMES:
        if name.endswith("-mesh"):
            continue
        if job_name.startswith(name.split("_", 1)[0]):
            return name
    return "04_very-simple"
