"""Path-tracing integrator: `lax.scan` over bounces, masked lanes.

TPU-first structure: no data-dependent control flow — every ray marches the
same fixed bounce count with an ``alive`` mask (dead lanes contribute
nothing); samples-per-pixel is a second ``lax.scan``; RNG is counter-based
(``jax.random.fold_in``) so any (frame, sample, pixel) is reproducible
without sequential state, which is what lets frames/tiles be rendered in any
order on any device.

Lighting: sun next-event-estimation (shadow ray per bounce) + emissive
spheres + sky on escape. Cosine-weighted hemisphere sampling.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from tpu_render_cluster.render.camera import Camera, camera_rays, scene_camera
from tpu_render_cluster.render.geometry import (
    EPS,
    INF,
    checker_albedo,
    intersect_scene,
    occluded_sun,
    sky_color,
)
from tpu_render_cluster.render.scene import Scene, build_scene


def _cosine_sample_hemisphere(normals, key):
    """Cosine-weighted directions about unit normals [R, 3]."""
    u1, u2 = jax.random.uniform(key, (2,) + normals.shape[:1])
    r = jnp.sqrt(u1)
    phi = 2.0 * jnp.pi * u2
    x = r * jnp.cos(phi)
    y = r * jnp.sin(phi)
    z = jnp.sqrt(jnp.maximum(0.0, 1.0 - u1))
    # Build a tangent frame per normal.
    helper = jnp.where(
        jnp.abs(normals[:, 0:1]) > 0.9,
        jnp.array([0.0, 1.0, 0.0])[None, :],
        jnp.array([1.0, 0.0, 0.0])[None, :],
    )
    tangent = jnp.cross(helper, normals)
    tangent = tangent / jnp.linalg.norm(tangent, axis=-1, keepdims=True)
    bitangent = jnp.cross(normals, tangent)
    return (
        x[:, None] * tangent + y[:, None] * bitangent + z[:, None] * normals
    )


def _shade_bounce(scene: Scene, carry, key, mesh=None):
    """One bounce; returns the new path state and this bounce's radiance
    CONTRIBUTION (not accumulated — the caller owns accumulation, which
    under re-sorting travels with the lane and unsorts once at the
    end)."""
    origins, directions, throughput, alive = carry
    radiance = jnp.zeros_like(throughput)
    t, sphere_index, is_plane = intersect_scene(scene, origins, directions)
    mesh_closer = None
    if mesh is not None:
        from tpu_render_cluster.render.mesh import intersect_instances

        # Dead lanes contribute nothing but would still drive the packet
        # walks with stale rays; replace them with guaranteed-miss rays so
        # blocks of compacted dead lanes (see _ray_sort_order) cull every
        # instance at the top level.
        mesh_origins = jnp.where(alive[:, None], origins, 1e7)
        mesh_directions = jnp.where(
            alive[:, None],
            directions,
            jnp.array([0.0, 1.0, 0.0], jnp.float32)[None, :],
        )
        # Seeding with the sphere/plane t culls mesh-instance walks the
        # known hit already beats; a mesh miss returns t_mesh == t, which
        # the strict < below reads as "not closer".
        t_mesh, mesh_normals, mesh_albedo = intersect_instances(
            mesh.bvh, mesh.instances, mesh_origins, mesh_directions,
            init_t=jnp.where(alive, t, INF),
        )
        mesh_closer = alive & (t_mesh < t)
        t = jnp.minimum(t, t_mesh)
        is_plane = is_plane & ~mesh_closer
    hit = t < INF

    # Escaped rays pick up the sky and die.
    sky = sky_color(scene, directions)
    radiance = radiance + throughput * sky * (alive & ~hit)[:, None]

    alive = alive & hit
    points = origins + directions * t[:, None]
    sphere_normals = (points - scene.centers[sphere_index]) / jnp.maximum(
        scene.radii[sphere_index][:, None], 1e-6
    )
    plane_normal = jnp.array([0.0, 1.0, 0.0], jnp.float32)
    normals = jnp.where(is_plane[:, None], plane_normal[None, :], sphere_normals)

    albedo = jnp.where(
        is_plane[:, None],
        checker_albedo(scene, points),
        scene.albedo[sphere_index],
    )
    emission = jnp.where(
        is_plane[:, None],
        jnp.zeros((1, 3), jnp.float32),
        scene.emission[sphere_index],
    )
    if mesh_closer is not None:
        normals = jnp.where(mesh_closer[:, None], mesh_normals, normals)
        albedo = jnp.where(mesh_closer[:, None], mesh_albedo, albedo)
        emission = jnp.where(
            mesh_closer[:, None], jnp.zeros((1, 3), jnp.float32), emission
        )
    radiance = radiance + throughput * emission * alive[:, None]

    # Sun next-event estimation (delta light -> single shadow ray).
    cos_sun = jnp.maximum(normals @ scene.sun_direction, 0.0)
    shadow_origin = points + normals * EPS * 4.0
    sun_dir = jnp.broadcast_to(scene.sun_direction, normals.shape)
    in_shadow = occluded_sun(scene, shadow_origin, sun_dir)
    if mesh is not None:
        from tpu_render_cluster.render.mesh import occluded_instances

        # Lanes whose shadow result can't matter stop driving the mesh
        # walks (the result folds the mask back in): already shadowed by
        # the sphere any-hit, dead, or facing away from the sun (their
        # direct term is zero regardless — cos_sun clamps to 0). The
        # spurious True for masked lanes is harmless because every use of
        # in_shadow is multiplied by cos_sun * alive.
        in_shadow = occluded_instances(
            mesh.bvh, mesh.instances, shadow_origin, sun_dir,
            already=in_shadow | ~alive | (cos_sun <= 0.0),
        )
    direct = (
        albedo
        * scene.sun_color[None, :]
        * (cos_sun * (~in_shadow) * alive)[:, None]
        / jnp.pi
    )
    radiance = radiance + throughput * direct

    # Continue the path: cosine sample (BRDF/pi * cos / pdf == albedo).
    throughput = throughput * jnp.where(alive[:, None], albedo, 1.0)
    new_directions = _cosine_sample_hemisphere(normals, key)
    new_origins = points + normals * EPS * 4.0
    origins = jnp.where(alive[:, None], new_origins, origins)
    directions = jnp.where(alive[:, None], new_directions, directions)
    return (origins, directions, throughput, radiance, alive)


def _ray_sort_order(origins, directions, alive, mesh=None):
    """Coherence key: candidate instance, then Morton cell + octant.

    Deep-mesh scenes walk the instanced BVH kernels in [block] packets; a
    packet's cost is the UNION of its lanes' traversals and its top-level
    instance cull only fires when NO lane touches the instance. Diffuse
    bounce rays scatter lanes all over the scene, so packets degrade to
    worst-case. Sorting each bounce's rays by (candidate instance, origin
    cell, direction octant) re-packs blocks into packets that (a) mostly
    want the SAME instance first — its walk then seeds tight per-lane
    best-t that culls the rest — and (b) are spatially/directionally
    coherent. Lane order is semantically free (each lane is an
    independent path; the caller unsorts at the end).
    """
    candidate = jnp.zeros((origins.shape[0],), jnp.uint32)
    if mesh is not None:
        from tpu_render_cluster.render import pallas_kernels as pk

        # Shared broadphase (one fused [R, K] slab pass, ~1 ms at render
        # ray counts): the ray's nearest-entry overlapped instance AABB,
        # K (=instances) for rays overlapping nothing — the same helper
        # the nearest wrapper derives its per-block candidates from.
        table = pk._instance_table(
            mesh.instances.rotation,
            mesh.instances.translation,
            mesh.instances.scale,
            mesh.bvh.bounds_min,
            mesh.bvh.bounds_max,
        )
        candidate = pk.instance_entry_candidates(
            origins, directions, table[:, 13:16], table[:, 16:19]
        ).astype(jnp.uint32)
    # Quantize origin + one unit of travel: for scattered bounce origins
    # this is origin clustering with a directional nudge; for the shared-
    # origin primary bounce (where origin cells degenerate to one) it
    # becomes a spatial clustering of directions on the view sphere, far
    # finer than the 3-bit octant alone.
    point = origins + directions
    lo = jnp.min(point, axis=0)
    span = jnp.maximum(jnp.max(point, axis=0) - lo, 1e-6)
    cell = ((point - lo) / span * 31.999).astype(jnp.uint32)  # 5 bits/axis

    def part1by2(v):
        # Spread 5 bits to every 3rd position (classic Morton dilation).
        v = (v | (v << 8)) & jnp.uint32(0x0300F)
        v = (v | (v << 4)) & jnp.uint32(0x030C3)
        v = (v | (v << 2)) & jnp.uint32(0x09249)
        return v

    morton = (
        part1by2(cell[:, 0])
        | (part1by2(cell[:, 1]) << 1)
        | (part1by2(cell[:, 2]) << 2)
    )
    octant = (
        (directions[:, 0] > 0).astype(jnp.uint32)
        | ((directions[:, 1] > 0).astype(jnp.uint32) << 1)
        | ((directions[:, 2] > 0).astype(jnp.uint32) << 2)
    )
    # Dead lanes compact to the tail: together with the dead-lane ray
    # masking in _shade_bounce, blocks that are entirely dead cull every
    # instance at the top level and cost almost nothing.
    dead = (~alive).astype(jnp.uint32) << 31
    # Key layout: octant bits 0-2, Morton bits 3-17, candidate bits 18-30,
    # dead flag bit 31. Candidate is clamped to 13 bits so a scene with
    # 64+ instances can't spill into the dead flag (or wrap the uint32)
    # and silently destroy the compaction this sort exists for.
    candidate = jnp.minimum(candidate, jnp.uint32(0x1FFF))
    return jnp.argsort((candidate << 18) | (morton << 3) | octant | dead)


def tile_base_key(frame, y0, x0):
    """The (frame, y0, x0)-derived RNG root every tile render uses."""
    return jax.random.fold_in(
        jax.random.fold_in(
            jax.random.fold_in(
                jax.random.PRNGKey(917), jnp.asarray(frame).astype(jnp.int32)
            ),
            jnp.asarray(y0, jnp.int32),
        ),
        jnp.asarray(x0, jnp.int32),
    )


def tile_trace_key(base_key):
    """The path-trace key for a tile (sample index -1 = the trace
    stream, disjoint from every per-sample jitter stream)."""
    return jax.random.fold_in(base_key, jnp.int32(-1))


def trace_seed(key):
    """int32 scalar driving the Pallas kernels' in-kernel counter PCG."""
    return jax.random.key_data(key).ravel()[-1].astype(jnp.int32)


def sample_jitter_rays(
    camera: Camera, key, *, width, height, y0, x0, tile_height, tile_width
):
    """One sample's jittered primary rays for a tile."""
    jitter_key, _ = jax.random.split(key)
    jitter = jax.random.uniform(jitter_key, (tile_height * tile_width, 2))
    return camera_rays(
        camera, width, height, y0=y0, x0=x0,
        tile_height=tile_height, tile_width=tile_width, jitter=jitter,
    )


def flat_sample_rays(
    camera: Camera, base_key, *, width, height, y0, x0, tile_height,
    tile_width, samples,
):
    """All samples' rays flattened onto the ray axis ([S * n, 3] x 2).

    ONE definition shared by render_tile's flattened branch and the
    wavefront driver (render/compaction._frame_rays): the masked-vs-
    wavefront equivalence rests on both tracing byte-identical rays with
    byte-identical RNG derivation, so the key schedule must not be able
    to drift between them.
    """
    n = tile_height * tile_width
    sample_keys = jax.vmap(lambda s: jax.random.fold_in(base_key, s))(
        jnp.arange(samples)
    )
    origins, directions = jax.vmap(
        lambda key: sample_jitter_rays(
            camera, key, width=width, height=height, y0=y0, x0=x0,
            tile_height=tile_height, tile_width=tile_width,
        )
    )(sample_keys)
    return origins.reshape(samples * n, 3), directions.reshape(samples * n, 3)


def frame_rays_and_seed(camera: Camera, frame, *, width, height, samples):
    """A full frame's flattened primary rays + its kernel trace seed.

    ONE definition (built on tile_base_key / flat_sample_rays /
    tile_trace_key / trace_seed) shared by the masked renderer's
    full-frame tile, the wavefront driver (compaction._frame_rays), and
    the ray-pool driver's vmapped multi-frame batch — all three provably
    trace the same physical rays with the same RNG derivation, so the
    cross-mode equivalence contracts cannot drift.
    """
    base_key = tile_base_key(frame, 0, 0)
    origins, directions = flat_sample_rays(
        camera, base_key, width=width, height=height, y0=0, x0=0,
        tile_height=height, tile_width=width, samples=samples,
    )
    return origins, directions, trace_seed(tile_trace_key(base_key))


def region_pixel_indices(*, y0, x0, tile_height, tile_width, width):
    """Row-major FULL-frame pixel indices of one region ([th*tw] int32).

    ``y0``/``x0`` may be traced scalars."""
    return (
        (jnp.arange(tile_height, dtype=jnp.int32)[:, None]
         + jnp.asarray(y0, jnp.int32)) * width
        + jnp.arange(tile_width, dtype=jnp.int32)[None, :]
        + jnp.asarray(x0, jnp.int32)
    ).reshape(-1)


def region_lane_map(
    *, y0, x0, tile_height, tile_width, width, height, samples
):
    """Local region-ray index -> FULL-frame lane id ([samples*th*tw] int32).

    THE lane-layout definition (sample-major over row-major pixels:
    ``s*H*W + y*W + x``) the cross-tier tiled-equals-untiled contract
    rests on — shared by ``region_rays_and_seed`` and the ray pool's
    region glane map so the two cannot drift.
    """
    pix = region_pixel_indices(
        y0=y0, x0=x0, tile_height=tile_height, tile_width=tile_width,
        width=width,
    )
    return (
        jnp.arange(samples, dtype=jnp.int32)[:, None] * (height * width)
        + pix[None, :]
    ).reshape(-1)


def region_rays_and_seed(
    camera: Camera, frame, *, width, height, samples, y0, x0,
    tile_height, tile_width,
):
    """One REGION's rows of the full frame's flattened primary rays, plus
    their GLOBAL lane ids and the frame's kernel trace seed.

    The cluster-tiling counterpart of ``frame_rays_and_seed``: instead of
    deriving a fresh RNG root from the tile coordinates (what
    ``render_tile(y0, x0)`` does — a different image per tiling), the
    region inherits the FULL frame's derivation. Per sample the whole
    frame's jitter array is drawn (cheap next to tracing) and sliced to
    the region's pixels, the camera rays are built from the same global
    pixel coordinates, and each ray carries its full-frame lane id
    ``s*H*W + y*W + x`` — the counter the Pallas kernels key their PCG
    streams on. Tracing these rays with these lane ids reproduces the
    whole-frame render's radiance at the region's pixels exactly, which
    is what makes a master-assembled tiled frame pixel-identical to the
    untiled render (tests/test_tiles.py pins it across all three
    execution tiers).

    ``y0``/``x0`` may be traced scalars (one compiled region program per
    tile SHAPE serves every tile position and frame).
    """
    base_key = tile_base_key(frame, 0, 0)
    n_frame = height * width
    pix = region_pixel_indices(
        y0=y0, x0=x0, tile_height=tile_height, tile_width=tile_width,
        width=width,
    )

    def one_sample(key):
        jitter_key, _ = jax.random.split(key)
        # The FULL frame's jitter, sliced: identical values to what
        # sample_jitter_rays feeds camera_rays for these pixels in the
        # whole-frame render.
        jitter = jax.random.uniform(jitter_key, (n_frame, 2))[pix]
        return camera_rays(
            camera, width, height, y0=y0, x0=x0,
            tile_height=tile_height, tile_width=tile_width, jitter=jitter,
        )

    sample_keys = jax.vmap(lambda s: jax.random.fold_in(base_key, s))(
        jnp.arange(samples)
    )
    origins, directions = jax.vmap(one_sample)(sample_keys)
    n_tile = tile_height * tile_width
    lanes = region_lane_map(
        y0=y0, x0=x0, tile_height=tile_height, tile_width=tile_width,
        width=width, height=height, samples=samples,
    )
    return (
        origins.reshape(samples * n_tile, 3),
        directions.reshape(samples * n_tile, 3),
        lanes,
        trace_seed(tile_trace_key(base_key)),
    )


def trace_paths(
    scene: Scene, origins, directions, key, *, max_bounces: int = 4, mesh=None,
    rng_lanes=None, use_tlas=None, quant=None,
) -> jnp.ndarray:
    """Trace one sample per ray; returns radiance [R, 3].

    On TPU this dispatches to the fused Pallas megakernel (the whole bounce
    loop in one kernel, path state VMEM-resident, counter-based in-kernel
    RNG — pallas_kernels.trace_paths_fused); elsewhere it runs the XLA
    bounce scan below. The two paths use different RNG streams but identical
    physics, so images agree statistically, not bit-for-bit.

    ``rng_lanes`` (optional [R] int32) overrides the RNG counter per ray:
    the region render path (cluster tiling) passes each ray's FULL-frame
    lane id so a cropped trace reproduces the whole-frame streams. Only
    meaningful on the Pallas paths — with it set, sphere and
    megakernel-eligible mesh scenes route through the per-bounce state-io
    kernels (which accept explicit lane ids; per-lane streams match the
    megakernels', pinned by tests/test_wavefront.py), and the XLA
    fallback ignores it (shape-derived RNG cannot be cropped — region
    renders there are statistically, not bitwise, consistent).

    ``use_tlas`` (None = the ``TRC_TLAS`` env tier, default on) selects
    the two-level TLAS kernel variants for mesh scenes. Per-lane results
    are identical either way (instance visit order is semantically free;
    only packet-cull efficiency changes); on the deep per-bounce path the
    TLAS kernels additionally emit the next bounce's coherence sort key
    from their epilogue, so the re-sort below reads one precomputed
    column instead of re-deriving keys from the full ray state.
    """
    from tpu_render_cluster.render import pallas_kernels

    if pallas_kernels.pallas_enabled():
        seed = trace_seed(key)
        if mesh is None and rng_lanes is None:
            return pallas_kernels.trace_paths_fused(
                scene, origins, directions, seed, max_bounces=max_bounces
            )
        if mesh is None:
            # Explicit lane ids: the SAME fused megakernel, with the RNG
            # counters read from the caller's lane row instead of the
            # launch position — a cropped region launch therefore runs
            # bitwise-identical per-lane math to the whole-frame render.
            return pallas_kernels.trace_paths_fused(
                scene, origins, directions, seed, max_bounces=max_bounces,
                lane=jnp.asarray(rng_lanes, jnp.int32),
            )
        # Mesh scenes: the megakernel (whole bounce loop incl. the
        # instanced BVH walk in one kernel) wins when the per-bounce walk
        # is shallow — its in-walk normal/albedo tracking adds work to
        # EVERY leaf visit, so deep-tree x many-instance scenes come out
        # behind the per-bounce instanced kernels (measured on-chip,
        # 256x256 4spp: 02_physics-mesh [3 nodes x 24 inst] 16.9 -> 38.9
        # f/s; 03_physics-2-mesh [127 nodes x 48 inst] 1.89 -> 1.52).
        if rng_lanes is None and pallas_kernels.mesh_megakernel_eligible(mesh):
            return pallas_kernels.trace_paths_fused_mesh(
                scene, mesh, origins, directions, seed,
                max_bounces=max_bounces, use_tlas=use_tlas, quant=quant,
            )
        # Deep scenes: the megakernel's bounce_step as ONE fused launch
        # per bounce (sphere/plane/mesh nearest, NEE with both any-hits,
        # shading, in-kernel PCG resample — pallas_kernels
        # mesh_bounce_pallas) with an XLA re-sort between bounces: rays
        # re-pack by (candidate instance, Morton cell, octant) with dead
        # lanes compacted to the tail, so the walks cull on tight
        # coherent packets. Travelling state rides ONE packed [n, 12]
        # gather incl. the accumulated radiance (separate [n, 3] gathers
        # measured ~3x slower: random-access cost is per-row, so packing
        # amortizes it); the carried lane index unsorts the radiance once
        # at the end.
        n = origins.shape[0]
        throughput = jnp.ones((n, 3), jnp.float32)
        radiance = jnp.zeros((n, 3), jnp.float32)
        alive = jnp.ones((n,), bool)
        lane = jnp.arange(n, dtype=jnp.int32)
        # The RNG counter rides separately from the unsort index when the
        # caller supplies full-frame lane ids (region rendering); with
        # positional lanes the two arrays are identical and XLA CSEs the
        # duplicate gathers away.
        rng = lane if rng_lanes is None else jnp.asarray(rng_lanes, jnp.int32)
        tlas = pallas_kernels.use_tlas_for(
            mesh.instances.translation.shape[0], use_tlas
        )
        quant = (
            pallas_kernels.bvh_quant_mode() if quant is None else int(quant)
        )
        keys = None
        if tlas:
            # Bounce 0 has no kernel-emitted key column yet: derive the
            # initial keys through the XLA twin of the kernels' fused
            # epilogue, via the SAME shared site the wavefront driver
            # uses (bit-identical derivation, pinned by
            # tests/test_tlas.py). Later bounces read the key column the
            # bounce kernel wrote while the state was still VMEM-resident.
            keys = pallas_kernels.initial_mesh_sort_keys(
                mesh, origins, directions, alive
            )
        for bounce in range(max_bounces):
            order = (
                jnp.argsort(keys) if tlas
                else _ray_sort_order(origins, directions, alive, mesh=mesh)
            )
            packed = jnp.concatenate(
                [origins, directions, throughput, radiance], axis=1
            )[order]
            origins = packed[:, 0:3]
            directions = packed[:, 3:6]
            throughput = packed[:, 6:9]
            radiance = packed[:, 9:12]
            alive = alive[order]
            lane = lane[order]
            rng = rng[order]
            # The sort key's dead flag (bit 31 flat, bit 29 fused) puts
            # every dead lane after every live one, so lanes >= live are
            # exactly the dead tail: the kernel's live-count prefetch
            # skips those blocks outright (behavior-preserving — dead
            # lanes pass through a masked bounce unchanged anyway). The
            # carried ORIGINAL lane id doubles as the RNG counter, so a
            # ray's stream survives the permutation (and composes with
            # the wavefront driver's compaction, which shares this
            # kernel).
            live = jnp.sum(alive.astype(jnp.int32))
            contribution, origins, directions, throughput, alive, keys = (
                pallas_kernels.mesh_bounce_pallas(
                    scene, mesh, origins, directions, throughput, alive,
                    seed, bounce, total_bounces=max_bounces,
                    lane=rng, live_count=live, use_tlas=tlas, quant=quant,
                )
            )
            radiance = radiance + contribution
        return jnp.zeros_like(radiance).at[lane].set(radiance)
    # Non-Pallas reference path: the plain XLA bounce loop. Order-invariant
    # per lane, so no sort machinery.
    n = origins.shape[0]
    throughput = jnp.ones((n, 3), jnp.float32)
    radiance = jnp.zeros((n, 3), jnp.float32)
    alive = jnp.ones((n,), bool)
    keys = jax.random.split(key, max_bounces)

    for bounce in range(max_bounces):
        origins, directions, throughput, contribution, alive = _shade_bounce(
            scene,
            (origins, directions, throughput, alive),
            keys[bounce],
            mesh=mesh,
        )
        radiance = radiance + contribution
    return radiance


@functools.partial(
    jax.jit,
    static_argnames=(
        "width", "height", "tile_height", "tile_width", "samples",
        "max_bounces", "use_tlas", "quant",
    ),
)
def render_tile(
    scene: Scene,
    camera: Camera,
    frame: jnp.ndarray,
    y0,
    x0,
    *,
    width: int,
    height: int,
    tile_height: int,
    tile_width: int,
    samples: int = 8,
    max_bounces: int = 4,
    mesh=None,
    use_tlas=None,
    quant=None,
) -> jnp.ndarray:
    """Render a tile; returns [tile_height, tile_width, 3] linear radiance.

    The RNG key derives from (frame, y0, x0, sample) so any tile of any
    frame renders identically regardless of device/order. ``use_tlas``
    (static; None = env tier) selects the two-level mesh kernel variant
    — a distinct value is a distinct compiled program, which is what
    lets the interleaved A/B bench run both variants in one process.
    """
    n = tile_height * tile_width
    base_key = tile_base_key(frame, y0, x0)

    from tpu_render_cluster.render import pallas_kernels

    # Samples always ride the ray axis under Pallas. Deep-walk mesh scenes
    # used to keep a sequential per-sample scan (flattening interleaved
    # jitter streams and widened the packets the BVH walk culls on —
    # measured 1.89 -> 1.64 f/s on 03_physics-2-mesh before re-sorting);
    # the per-bounce Morton re-sort in trace_paths now re-packs the
    # flattened rays into coherent blocks regardless of sample
    # interleaving, so flattening is a pure win (4x fewer kernel launches
    # for the same total work).
    flatten_samples = pallas_kernels.pallas_enabled()
    if flatten_samples:
        # Samples ride the ray axis instead of a sequential lax.scan: one
        # [samples * n]-ray trace keeps every bounce step 'samples'x larger
        # (better VPU/MXU occupancy, fewer serialized steps) for the same
        # total work — a measured ~1.9x on a single chip. Safe here because
        # the fused kernel blocks rays at BLOCK_R; its VMEM working set is
        # independent of the flattened ray count.
        origins, directions = flat_sample_rays(
            camera, base_key, width=width, height=height, y0=y0, x0=x0,
            tile_height=tile_height, tile_width=tile_width, samples=samples,
        )
        radiance = trace_paths(
            scene,
            origins,
            directions,
            tile_trace_key(base_key),
            max_bounces=max_bounces,
            mesh=mesh,
            use_tlas=use_tlas,
            quant=quant,
        )
        image = radiance.reshape(samples, n, 3).mean(axis=0)
    else:
        # The XLA fallback materializes [R, N] intersection intermediates,
        # so the flattened [samples * n] ray axis would multiply peak memory
        # by 'samples' (an OOM risk for big tiles on CPU/GPU workers); keep
        # the sequential per-sample scan there instead.
        sample_keys = jax.vmap(lambda s: jax.random.fold_in(base_key, s))(
            jnp.arange(samples)
        )

        def sample_step(acc, key):
            origins, directions = sample_jitter_rays(
                camera, key, width=width, height=height, y0=y0, x0=x0,
                tile_height=tile_height, tile_width=tile_width,
            )
            _, trace_key = jax.random.split(key)
            radiance = trace_paths(
                scene, origins, directions, trace_key,
                max_bounces=max_bounces, mesh=mesh,
            )
            return acc + radiance, None

        total, _ = jax.lax.scan(
            sample_step, jnp.zeros((n, 3), jnp.float32), sample_keys
        )
        image = total / samples
    return image.reshape(tile_height, tile_width, 3)


def render_frame(
    scene_name: str,
    frame_index: int,
    *,
    width: int = 512,
    height: int = 512,
    samples: int = 8,
    max_bounces: int = 4,
    tile_size: int | None = None,
) -> jnp.ndarray:
    """Render a full frame on the default device; returns [H, W, 3] linear."""
    from tpu_render_cluster.render.mesh import scene_mesh_set

    scene = build_scene(scene_name, frame_index)
    camera = scene_camera(scene_name, frame_index)
    # BVH env tiers resolve HERE, outside the jitted tile renders.
    _tlas, bvh_quant, bvh_builder, bvh_wide = resolve_bvh_config()
    mesh = scene_mesh_set(scene_name, frame_index, bvh_builder, bvh_wide)
    frame = jnp.asarray(frame_index, jnp.float32)
    if tile_size is None:
        return render_tile(
            scene,
            camera,
            frame,
            0,
            0,
            width=width,
            height=height,
            tile_height=height,
            tile_width=width,
            samples=samples,
            max_bounces=max_bounces,
            mesh=mesh,
            quant=bvh_quant,
        )
    rows = []
    for y0 in range(0, height, tile_size):
        row = []
        for x0 in range(0, width, tile_size):
            row.append(
                render_tile(
                    scene,
                    camera,
                    frame,
                    y0,
                    x0,
                    width=width,
                    height=height,
                    tile_height=min(tile_size, height - y0),
                    tile_width=min(tile_size, width - x0),
                    samples=samples,
                    max_bounces=max_bounces,
                    mesh=mesh,
                    quant=bvh_quant,
                )
            )
        rows.append(jnp.concatenate(row, axis=1))
    return jnp.concatenate(rows, axis=0)


def tonemap(image: jnp.ndarray) -> jnp.ndarray:
    """Linear -> display: Reinhard + gamma 2.2, uint8."""
    mapped = image / (1.0 + image)
    srgb = jnp.power(jnp.clip(mapped, 0.0, 1.0), 1.0 / 2.2)
    return (srgb * 255.0 + 0.5).astype(jnp.uint8)


def resolve_bvh_config(use_tlas=None, quant=None, builder=None, wide=None):
    """Resolve the BVH env tiers (``TRC_TLAS``/``TRC_BVH_QUANT``/
    ``TRC_BVH_BUILDER``/``TRC_BVH_WIDE``) to concrete values — the ONE
    site the jitted renderer factories resolve them through, OUTSIDE any
    trace (the ``env-tiers`` lint contract), so a mid-process env toggle
    resolves to a fresh cache key instead of a stale compiled program or
    tree."""
    from tpu_render_cluster.render.mesh import bvh_builder, bvh_wide
    from tpu_render_cluster.render.pallas_kernels import (
        bvh_quant_mode,
        tlas_enabled,
    )

    return (
        tlas_enabled() if use_tlas is None else bool(use_tlas),
        bvh_quant_mode() if quant is None else max(0, min(int(quant), 2)),
        bvh_builder() if builder is None else str(builder),
        bvh_wide() if wide is None else max(1, min(int(wide), 8)),
    )


@functools.lru_cache(maxsize=32)
def _fused_frame_renderer(
    scene_name: str,
    width: int,
    height: int,
    samples: int,
    max_bounces: int,
    use_tlas: bool,
    quant: int,
    builder: str,
    wide: int,
):
    from tpu_render_cluster.render.camera import scene_camera
    from tpu_render_cluster.render.scene import build_scene

    @jax.jit
    def render(frame: jnp.ndarray) -> jnp.ndarray:
        from tpu_render_cluster.render.mesh import scene_mesh_set

        scene = build_scene(scene_name, frame)
        camera = scene_camera(scene_name, frame)
        mesh = scene_mesh_set(scene_name, frame, builder, wide)
        linear = render_tile(
            scene,
            camera,
            jnp.asarray(frame, jnp.float32),
            0,
            0,
            width=width,
            height=height,
            tile_height=height,
            tile_width=width,
            samples=samples,
            max_bounces=max_bounces,
            mesh=mesh,
            use_tlas=use_tlas,
            quant=quant,
        )
        return tonemap(linear)

    # Roofline profiling (obs/profiling.py): the first call captures the
    # program's XLA cost analysis (FLOPs/bytes) under the masked tier's
    # kernel key; the lru_cache above caches the instrumented wrapper, so
    # later frames pay one flag check. The tlas/quant/bvh dims key every
    # node-format variant to its own roofline row — the per-kernel
    # placement deltas bench.py --bvh-compare records.
    from tpu_render_cluster.obs.profiling import (
        bvh_dims,
        get_profiler,
        kernel_key,
    )

    return get_profiler().instrument(
        kernel_key(
            "masked", scene_name,
            w=width, h=height, s=samples, b=max_bounces,
            **bvh_dims(tlas=use_tlas, quant=quant, builder=builder,
                       wide=wide),
        ),
        render,
    )


def fused_frame_renderer(
    scene_name: str,
    width: int,
    height: int,
    samples: int,
    max_bounces: int,
    use_tlas: bool | None = None,
    quant: int | None = None,
    builder: str | None = None,
    wide: int | None = None,
):
    """A jitted ``frame -> uint8 [H, W, 3]`` closure for one scene/config.

    Fuses scene build + camera + path trace + tonemap into a single XLA
    program, so rendering a frame is ONE device dispatch. The eager
    alternative (build_scene / scene_camera outside jit, as render_frame
    does) pays a device round-trip per tiny scene array — tens of
    dispatches per frame, which dominates wall time when the device sits
    behind a network tunnel (observed: ~2 s/frame eager vs ~10 ms fused on
    the same chip).

    ``use_tlas``/``quant``/``builder``/``wide`` (None = env tiers,
    resolved HERE — outside the trace) are part of the cache key AND the
    compiled program's identity: the interleaved ``bench.py
    --bvh-compare`` holds one renderer per node-format variant in the
    same process, and an env toggle between calls gets a fresh renderer
    with a matching tree instead of a stale cache hit.
    """
    return _fused_frame_renderer(
        scene_name, width, height, samples, max_bounces,
        *resolve_bvh_config(use_tlas, quant, builder, wide),
    )


fused_frame_renderer.cache_clear = _fused_frame_renderer.cache_clear


@functools.lru_cache(maxsize=64)
def _fused_region_renderer(
    scene_name: str,
    width: int,
    height: int,
    tile_height: int,
    tile_width: int,
    samples: int,
    max_bounces: int,
    use_tlas: bool,
    quant: int,
    builder: str,
    wide: int,
):
    from tpu_render_cluster.render.camera import scene_camera
    from tpu_render_cluster.render.scene import build_scene

    @jax.jit
    def render(frame: jnp.ndarray, y0, x0) -> jnp.ndarray:
        from tpu_render_cluster.render.mesh import scene_mesh_set

        scene = build_scene(scene_name, frame)
        camera = scene_camera(scene_name, frame)
        mesh = scene_mesh_set(scene_name, frame, builder, wide)
        origins, directions, lanes, seed = region_rays_and_seed(
            camera, jnp.asarray(frame, jnp.float32),
            width=width, height=height, samples=samples,
            y0=y0, x0=x0, tile_height=tile_height, tile_width=tile_width,
        )
        base_key = tile_base_key(jnp.asarray(frame, jnp.float32), 0, 0)
        n = tile_height * tile_width
        from tpu_render_cluster.render import pallas_kernels

        if pallas_kernels.pallas_enabled():
            radiance = trace_paths(
                scene, origins, directions, tile_trace_key(base_key),
                max_bounces=max_bounces, mesh=mesh, rng_lanes=lanes,
                use_tlas=use_tlas, quant=quant,
            )
        else:
            # XLA fallback: per-lane counters don't exist there, so the
            # region renders with its own shape-derived streams —
            # statistically the same image, not bitwise (the Pallas tiers
            # carry the exactness contract).
            radiance = trace_paths(
                scene, origins, directions, tile_trace_key(base_key),
                max_bounces=max_bounces, mesh=mesh,
            )
        return radiance.reshape(samples, n, 3).mean(axis=0).reshape(
            tile_height, tile_width, 3
        )

    # Roofline profiling: one cost capture per tile SHAPE (matching the
    # one-compile-per-shape contract of this renderer).
    from tpu_render_cluster.obs.profiling import (
        bvh_dims,
        get_profiler,
        kernel_key,
    )

    return get_profiler().instrument(
        kernel_key(
            "region", scene_name,
            w=width, h=height, th=tile_height, tw=tile_width,
            s=samples, b=max_bounces,
            **bvh_dims(tlas=use_tlas, quant=quant, builder=builder,
                       wide=wide),
        ),
        render,
    )


def fused_region_renderer(
    scene_name: str,
    width: int,
    height: int,
    tile_height: int,
    tile_width: int,
    samples: int,
    max_bounces: int,
    use_tlas: bool | None = None,
    quant: int | None = None,
    builder: str | None = None,
    wide: int | None = None,
):
    """A jitted ``(frame, y0, x0) -> [th, tw, 3] LINEAR`` region closure.

    The masked execution tier's cluster-tile path: one compiled program
    per tile SHAPE (``y0``/``x0`` are traced), so every tile of a grid —
    and every frame — reuses the same executable. The region traces the
    full frame's rays-and-RNG restricted to its pixels
    (``region_rays_and_seed``), so stitching a grid of regions is
    pixel-identical to the whole-frame render (up to the FP ties of the
    megakernel-vs-state-io kernel pairing; see ``trace_paths``).

    Returns LINEAR radiance (not tonemapped): callers tonemap after
    (matching render_frame's contract) so the assembly seam test can
    compare linear images. BVH node-format knobs resolve like
    ``fused_frame_renderer``'s — outside the trace, into the cache key.
    """
    return _fused_region_renderer(
        scene_name, width, height, tile_height, tile_width, samples,
        max_bounces, *resolve_bvh_config(use_tlas, quant, builder, wide),
    )


fused_region_renderer.cache_clear = _fused_region_renderer.cache_clear


def render_frame_region(
    scene_name: str,
    frame_index: int,
    *,
    y0: int,
    x0: int,
    tile_height: int,
    tile_width: int,
    width: int = 512,
    height: int = 512,
    samples: int = 8,
    max_bounces: int = 4,
) -> jnp.ndarray:
    """Render one region of a frame; [tile_height, tile_width, 3] linear.

    Equals the whole-frame render's pixels on the region (the cluster
    tiling contract) — see ``fused_region_renderer``.
    """
    return fused_region_renderer(
        scene_name, width, height, tile_height, tile_width, samples,
        max_bounces,
    )(jnp.asarray(frame_index, jnp.float32), y0, x0)
