from tpu_render_cluster.jobs.models import (
    BlenderJob,
    DistributionStrategy,
    DynamicStrategyOptions,
    EagerNaiveCoarseOptions,
    TpuBatchStrategyOptions,
)

__all__ = [
    "BlenderJob",
    "DistributionStrategy",
    "DynamicStrategyOptions",
    "EagerNaiveCoarseOptions",
    "TpuBatchStrategyOptions",
]
