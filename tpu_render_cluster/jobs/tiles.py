"""The sub-frame work unit: ``(frame_index, tile)``.

PR 7 extends the cluster's atom of distribution from a whole frame to an
image tile, so one large frame can spread across every idle worker and
per-frame LATENCY (not just throughput) scales with cluster size. This
module is the single definition site for the unit key and the tile
geometry — master state, the queue mirrors, the worker queue, and the
renderer's region path all normalize through here so frame-keyed callers
cannot drift from tile-keyed ones.

Conventions:

- ``tile is None`` means the whole frame — the pre-tiling work unit. All
  wire traffic for whole-frame jobs omits the tile key entirely and stays
  byte-identical to the reference protocol (C++ workers interoperate
  unmodified on whole-frame jobs).
- A tiled job carries a grid ``(rows, cols)``; tiles are indexed row-major
  ``0 .. rows*cols - 1``. Tile PIXEL bounds are derived from the grid and
  the render resolution by ``tile_bounds`` (the renderer's resolution is
  backend configuration, so the wire carries only the grid + index).
"""

from __future__ import annotations

from typing import NamedTuple
from tpu_render_cluster.utils.env import env_str

# Grid ceiling: the unit tables, mirrors, and the assembly ledger are all
# O(tiles) per frame, and a 16x16 grid already turns one frame into 256
# schedulable units — far past the point where per-unit RPC overhead
# dominates. Guarded at job validation time.
MAX_TILE_GRID_DIM = 16


class WorkUnit(NamedTuple):
    """One schedulable unit of work: a frame, or one tile of a frame."""

    frame_index: int
    tile: int | None = None  # None = whole frame (reference behavior)

    @property
    def is_tiled(self) -> bool:
        return self.tile is not None

    @property
    def sort_key(self) -> tuple[int, int]:
        """Total order that never compares ``None`` to an int (a job's
        units are uniformly tiled or uniformly whole-frame, but cross-job
        collections — goodbye sweeps, ghost listings — mix both)."""
        return (self.frame_index, -1 if self.tile is None else self.tile)

    @property
    def label(self) -> str:
        """Log/span label: ``"12"`` for a frame, ``"12/t03"`` for a tile."""
        if self.tile is None:
            return str(self.frame_index)
        return f"{self.frame_index}/t{self.tile:02d}"


def parse_tile_grid(text: str) -> tuple[int, int]:
    """Parse ``TRC_TILE_GRID``: ``"2x2"``, ``"2,3"``, or ``"4"`` (square)."""
    cleaned = text.strip().lower().replace("x", ",")
    parts = [p for p in cleaned.split(",") if p.strip()]
    if len(parts) == 1:
        rows = cols = int(parts[0])
    elif len(parts) == 2:
        rows, cols = int(parts[0]), int(parts[1])
    else:
        raise ValueError(f"Unparseable tile grid: {text!r} (want ROWSxCOLS)")
    validate_tile_grid((rows, cols))
    return rows, cols


def env_tile_grid() -> tuple[int, int] | None:
    """The ``TRC_TILE_GRID`` default grid for jobs loaded from TOML files
    that don't specify one. Read at job LOAD time only — never while
    decoding wire payloads, so a worker's environment cannot reinterpret
    a job the master defined."""
    value = (env_str("TRC_TILE_GRID") or "").strip()
    if not value or value in ("0", "off", "none", "1", "1x1"):
        return None
    return parse_tile_grid(value)


def validate_tile_grid(grid: tuple[int, int]) -> None:
    rows, cols = grid
    if rows < 1 or cols < 1:
        raise ValueError(f"tile grid dimensions must be >= 1, got {rows}x{cols}")
    if rows > MAX_TILE_GRID_DIM or cols > MAX_TILE_GRID_DIM:
        raise ValueError(
            f"tile grid {rows}x{cols} exceeds the {MAX_TILE_GRID_DIM}x"
            f"{MAX_TILE_GRID_DIM} ceiling"
        )


def tile_rc(tile: int, grid: tuple[int, int]) -> tuple[int, int]:
    """Row-major (row, col) of a tile index within the grid."""
    rows, cols = grid
    if not (0 <= tile < rows * cols):
        raise ValueError(f"tile {tile} outside the {rows}x{cols} grid")
    return tile // cols, tile % cols


def tile_pixel_fraction(
    tile: int | None,
    grid: tuple[int, int] | None,
    *,
    width: int | None = None,
    height: int | None = None,
) -> float:
    """Fraction of the frame's pixels a tile covers (1.0 = whole frame).

    With the render resolution the bounds are exact; without it the
    even-split geometry guarantees every tile is within one pixel per
    axis of ``1 / (rows * cols)``, so that is the resolution-free answer.
    The scheduler's cost model uses this to price a ``(frame, tile)``
    unit at its share of the frame instead of the whole frame's predicted
    cost (tiled jobs were uniformly overpriced before).
    """
    if tile is None or grid is None:
        return 1.0
    rows, cols = grid
    if width is not None and height is not None:
        y0, x0, tile_height, tile_width = tile_bounds(
            tile, grid, width=width, height=height
        )
        total = width * height
        return (tile_height * tile_width) / total if total else 1.0
    return 1.0 / (rows * cols)


def unit_pixel_fraction(
    unit: WorkUnit,
    grid: tuple[int, int] | None,
    *,
    width: int | None = None,
    height: int | None = None,
) -> float:
    """``tile_pixel_fraction`` keyed by a WorkUnit."""
    return tile_pixel_fraction(unit.tile, grid, width=width, height=height)


def tile_bounds(
    tile: int, grid: tuple[int, int], *, width: int, height: int
) -> tuple[int, int, int, int]:
    """Pixel bounds ``(y0, x0, tile_height, tile_width)`` of a tile.

    Even split with the remainder spread over the leading rows/cols
    (``floor(i*H/rows)`` boundaries), so tiles differ by at most one
    pixel per axis and the union over the grid is exactly the frame.
    """
    row, col = tile_rc(tile, grid)
    rows, cols = grid
    y0 = row * height // rows
    y1 = (row + 1) * height // rows
    x0 = col * width // cols
    x1 = (col + 1) * width // cols
    return y0, x0, y1 - y0, x1 - x0
