"""Job definition model.

The job schema matches the reference's ``BlenderJob`` TOML contract
(reference: shared/src/jobs/mod.rs:7-101): job name/description, project
file + render script paths (with %BASE% placeholder support), inclusive
frame range, the worker-count barrier, an internally-tagged distribution
strategy, and output directory / name format / file format.

New in this build: the ``tpu-batch`` strategy (cost-matrix assignment solved
on TPU, see tpu_render_cluster/master/tpu_batch.py) and an optional
``render_backend`` hint ('blender' | 'tpu-raytrace') that workers may use as
a default when no CLI backend is given. Both are backward compatible: the
reference's job TOMLs parse unchanged, and serialisation of the three
reference strategies is byte-identical in structure.
"""

from __future__ import annotations

try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11
    import tomli as tomllib  # type: ignore[no-redef]
from dataclasses import dataclass
from pathlib import Path
from typing import Any


@dataclass(frozen=True)
class DynamicStrategyOptions:
    """Tuning knobs of the dynamic work-stealing strategy.

    Reference: shared/src/jobs/mod.rs:8-30.
    """

    target_queue_size: int
    min_queue_size_to_steal: int
    min_seconds_before_resteal_to_elsewhere: int
    min_seconds_before_resteal_to_original_worker: int


@dataclass(frozen=True)
class EagerNaiveCoarseOptions:
    target_queue_size: int


@dataclass(frozen=True)
class TpuBatchStrategyOptions:
    """Tuning knobs of the TPU cost-matrix scheduler (new in this build).

    The scheduler keeps every worker's queue topped up to
    ``target_queue_size`` like eager-naive-coarse, but chooses *which* frame
    goes to *which* worker by solving a batched assignment problem on TPU
    (predicted frame time x worker load), and steals from overloaded workers
    like the dynamic strategy when the pending pool runs dry.
    """

    target_queue_size: int = 4
    min_queue_size_to_steal: int = 2
    min_seconds_before_resteal_to_elsewhere: int = 40
    min_seconds_before_resteal_to_original_worker: int = 80
    # EMA smoothing factor for per-worker frame-time prediction.
    cost_ema_alpha: float = 0.3


@dataclass(frozen=True)
class JobSlo:
    """Per-job service-level objectives (new; absent from reference TOMLs).

    Declared in the job TOML as an ``[slo]`` table; the master's SLO
    engine (obs/slo.py) tracks attainment and multi-window burn rate
    online and fires structured alerts when an objective burns.

    - ``unit_latency_p99_seconds``: 99% of work units must go
      dispatch-to-result within this bound (measured on the
      ``master_unit_latency_seconds`` stream);
    - ``deadline_seconds``: the whole job must finish within this many
      seconds of starting.
    """

    unit_latency_p99_seconds: float | None = None
    deadline_seconds: float | None = None

    def __post_init__(self) -> None:
        problems = []
        for name in ("unit_latency_p99_seconds", "deadline_seconds"):
            value = getattr(self, name)
            # bool is an int subclass: `deadline_seconds = true` in TOML
            # must be an error, not a 1-second objective.
            if value is not None and (
                isinstance(value, bool)
                or not isinstance(value, (int, float))
                or not value > 0
            ):
                problems.append(f"slo.{name} must be a positive number, got {value!r}")
        if (
            self.unit_latency_p99_seconds is None
            and self.deadline_seconds is None
        ):
            problems.append(
                "[slo] table declares no objective (set "
                "unit_latency_p99_seconds and/or deadline_seconds)"
            )
        if problems:
            raise ValueError("; ".join(problems))

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {}
        if self.unit_latency_p99_seconds is not None:
            out["unit_latency_p99_seconds"] = self.unit_latency_p99_seconds
        if self.deadline_seconds is not None:
            out["deadline_seconds"] = self.deadline_seconds
        return out

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "JobSlo":
        if not isinstance(data, dict):
            raise ValueError(f"slo must be a table, got {data!r}")
        unknown = set(data) - {"unit_latency_p99_seconds", "deadline_seconds"}
        if unknown:
            raise ValueError(f"unknown slo key(s): {sorted(unknown)}")
        def _num(key: str):
            value = data.get(key)
            if isinstance(value, (int, float)) and not isinstance(value, bool):
                return float(value)
            return value  # __post_init__ rejects non-numbers (incl. bools)
        return cls(
            unit_latency_p99_seconds=_num("unit_latency_p99_seconds"),
            deadline_seconds=_num("deadline_seconds"),
        )


STRATEGY_NAIVE_FINE = "naive-fine"
STRATEGY_EAGER_NAIVE_COARSE = "eager-naive-coarse"
STRATEGY_DYNAMIC = "dynamic"
STRATEGY_TPU_BATCH = "tpu-batch"


@dataclass(frozen=True)
class DistributionStrategy:
    """Internally-tagged strategy enum.

    Serialised as ``{"strategy_type": "...", ...options}`` exactly like the
    reference's serde representation (shared/src/jobs/mod.rs:32-43), so the
    analysis suite's ``FrameDistributionStrategy.from_raw_data`` keeps
    working (analysis/core/models.py:16-27).
    """

    strategy_type: str
    eager: EagerNaiveCoarseOptions | None = None
    dynamic: DynamicStrategyOptions | None = None
    tpu_batch: TpuBatchStrategyOptions | None = None

    # -- constructors ------------------------------------------------------

    @classmethod
    def naive_fine(cls) -> "DistributionStrategy":
        return cls(STRATEGY_NAIVE_FINE)

    @classmethod
    def eager_naive_coarse(cls, target_queue_size: int) -> "DistributionStrategy":
        return cls(
            STRATEGY_EAGER_NAIVE_COARSE,
            eager=EagerNaiveCoarseOptions(target_queue_size),
        )

    @classmethod
    def dynamic_strategy(cls, options: DynamicStrategyOptions) -> "DistributionStrategy":
        return cls(STRATEGY_DYNAMIC, dynamic=options)

    @classmethod
    def tpu_batch_strategy(cls, options: TpuBatchStrategyOptions | None = None) -> "DistributionStrategy":
        return cls(STRATEGY_TPU_BATCH, tpu_batch=options or TpuBatchStrategyOptions())

    # -- serde -------------------------------------------------------------

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {"strategy_type": self.strategy_type}
        if self.strategy_type == STRATEGY_EAGER_NAIVE_COARSE:
            assert self.eager is not None
            out["target_queue_size"] = self.eager.target_queue_size
        elif self.strategy_type == STRATEGY_DYNAMIC:
            assert self.dynamic is not None
            out["target_queue_size"] = self.dynamic.target_queue_size
            out["min_queue_size_to_steal"] = self.dynamic.min_queue_size_to_steal
            out["min_seconds_before_resteal_to_elsewhere"] = (
                self.dynamic.min_seconds_before_resteal_to_elsewhere
            )
            out["min_seconds_before_resteal_to_original_worker"] = (
                self.dynamic.min_seconds_before_resteal_to_original_worker
            )
        elif self.strategy_type == STRATEGY_TPU_BATCH:
            assert self.tpu_batch is not None
            out["target_queue_size"] = self.tpu_batch.target_queue_size
            out["min_queue_size_to_steal"] = self.tpu_batch.min_queue_size_to_steal
            out["min_seconds_before_resteal_to_elsewhere"] = (
                self.tpu_batch.min_seconds_before_resteal_to_elsewhere
            )
            out["min_seconds_before_resteal_to_original_worker"] = (
                self.tpu_batch.min_seconds_before_resteal_to_original_worker
            )
            out["cost_ema_alpha"] = self.tpu_batch.cost_ema_alpha
        return out

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "DistributionStrategy":
        strategy_type = str(data["strategy_type"])
        if strategy_type == STRATEGY_NAIVE_FINE:
            return cls.naive_fine()
        if strategy_type == STRATEGY_EAGER_NAIVE_COARSE:
            return cls.eager_naive_coarse(int(data["target_queue_size"]))
        if strategy_type == STRATEGY_DYNAMIC:
            return cls.dynamic_strategy(
                DynamicStrategyOptions(
                    target_queue_size=int(data["target_queue_size"]),
                    min_queue_size_to_steal=int(data["min_queue_size_to_steal"]),
                    min_seconds_before_resteal_to_elsewhere=int(
                        data["min_seconds_before_resteal_to_elsewhere"]
                    ),
                    min_seconds_before_resteal_to_original_worker=int(
                        data["min_seconds_before_resteal_to_original_worker"]
                    ),
                )
            )
        if strategy_type == STRATEGY_TPU_BATCH:
            return cls.tpu_batch_strategy(
                TpuBatchStrategyOptions(
                    target_queue_size=int(data.get("target_queue_size", 4)),
                    min_queue_size_to_steal=int(data.get("min_queue_size_to_steal", 2)),
                    min_seconds_before_resteal_to_elsewhere=int(
                        data.get("min_seconds_before_resteal_to_elsewhere", 40)
                    ),
                    min_seconds_before_resteal_to_original_worker=int(
                        data.get("min_seconds_before_resteal_to_original_worker", 80)
                    ),
                    cost_ema_alpha=float(data.get("cost_ema_alpha", 0.3)),
                )
            )
        raise ValueError(f"Unknown strategy_type: {strategy_type!r}")


@dataclass(frozen=True)
class BlenderJob:
    """A render job definition (reference: shared/src/jobs/mod.rs:46-81)."""

    job_name: str
    job_description: str | None
    project_file_path: str
    render_script_path: str
    frame_range_from: int  # inclusive
    frame_range_to: int  # inclusive
    wait_for_number_of_workers: int
    frame_distribution_strategy: DistributionStrategy
    output_directory_path: str
    output_file_name_format: str
    output_file_format: str
    # New (optional, absent from reference TOMLs): default worker backend hint.
    render_backend: str | None = None
    # New (optional): sub-frame tile grid ``(rows, cols)``. When set, the
    # unit of distribution becomes ``(frame, tile)`` — every frame splits
    # into rows*cols independently schedulable tiles that the master
    # re-assembles (master/assembly.py). None (the reference contract)
    # keeps whole-frame units and byte-identical wire traffic.
    tile_grid: tuple[int, int] | None = None
    # New (optional): per-job service-level objectives ([slo] TOML table).
    # Master-side only — workers ignore it; absent = no SLO tracking and
    # reference-identical serialization.
    slo: JobSlo | None = None

    def __post_init__(self) -> None:
        """Reject structurally-broken jobs at load time, not mid-dispatch.

        The reference accepts any TOML that parses and fails much later
        (an inverted frame range yields a job that 'finishes' instantly
        with zero frames; an empty project path dies inside Blender).
        With the multi-job scheduler admitting jobs from remote clients,
        a clear submit-time error is the contract.
        """
        problems = []
        if not self.job_name.strip():
            problems.append("job_name must be non-empty")
        if self.frame_range_to < self.frame_range_from:
            problems.append(
                f"frame range is inverted: frame_range_from={self.frame_range_from} "
                f"> frame_range_to={self.frame_range_to}"
            )
        if not self.project_file_path.strip():
            problems.append("project_file_path must be non-empty")
        if not self.render_script_path.strip():
            problems.append("render_script_path must be non-empty")
        if not self.output_directory_path.strip():
            problems.append("output_directory_path must be non-empty")
        if self.wait_for_number_of_workers < 1:
            problems.append(
                "wait_for_number_of_workers must be >= 1, got "
                f"{self.wait_for_number_of_workers}"
            )
        if self.tile_grid is not None:
            from tpu_render_cluster.jobs.tiles import validate_tile_grid

            # Normalize to the canonical int tuple before validating
            # (frozen dataclass: go through __setattr__ like __post_init__
            # frameworks do). Anything non-[rows, cols]-shaped — a string,
            # mixed types, wrong arity — lands in the aggregated
            # 'Invalid job' report like every other field.
            if isinstance(self.tile_grid, (str, bytes)):
                grid = None  # "22" must not silently iterate into (2, 2)
            else:
                try:
                    grid = tuple(int(v) for v in self.tile_grid)
                except (TypeError, ValueError):
                    grid = None
            if grid is None or len(grid) != 2:
                problems.append(
                    f"tiles must be [rows, cols], got {self.tile_grid!r}"
                )
            else:
                object.__setattr__(self, "tile_grid", grid)
                try:
                    validate_tile_grid(grid)
                except ValueError as e:
                    problems.append(str(e))
        if self.slo is not None and not isinstance(self.slo, JobSlo):
            # Raw TOML table through from_dict: normalize like tile_grid,
            # landing malformed declarations in the aggregated report.
            try:
                object.__setattr__(self, "slo", JobSlo.from_dict(self.slo))
            except ValueError as e:
                problems.append(str(e))
                object.__setattr__(self, "slo", None)
        if problems:
            raise ValueError(
                f"Invalid job {self.job_name!r}: " + "; ".join(problems)
            )

    # -- derived -----------------------------------------------------------

    def frame_indices(self) -> range:
        return range(self.frame_range_from, self.frame_range_to + 1)

    def frame_count(self) -> int:
        return self.frame_range_to - self.frame_range_from + 1

    def tiles_per_frame(self) -> int:
        if self.tile_grid is None:
            return 1
        return self.tile_grid[0] * self.tile_grid[1]

    def work_units(self):
        """Every schedulable unit: frames, or (frame, tile) pairs, in
        frame-major tile-minor order."""
        from tpu_render_cluster.jobs.tiles import WorkUnit

        for frame_index in self.frame_indices():
            if self.tile_grid is None:
                yield WorkUnit(frame_index)
            else:
                for tile in range(self.tiles_per_frame()):
                    yield WorkUnit(frame_index, tile)

    def unit_count(self) -> int:
        return self.frame_count() * self.tiles_per_frame()

    # -- serde -------------------------------------------------------------

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "job_name": self.job_name,
            "job_description": self.job_description,
            "project_file_path": self.project_file_path,
            "render_script_path": self.render_script_path,
            "frame_range_from": self.frame_range_from,
            "frame_range_to": self.frame_range_to,
            "wait_for_number_of_workers": self.wait_for_number_of_workers,
            "frame_distribution_strategy": self.frame_distribution_strategy.to_dict(),
            "output_directory_path": self.output_directory_path,
            "output_file_name_format": self.output_file_name_format,
            "output_file_format": self.output_file_format,
        }
        if self.render_backend is not None:
            out["render_backend"] = self.render_backend
        if self.tile_grid is not None:
            out["tiles"] = list(self.tile_grid)
        if self.slo is not None:
            out["slo"] = self.slo.to_dict()
        return out

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "BlenderJob":
        return cls(
            job_name=str(data["job_name"]),
            job_description=data.get("job_description"),
            project_file_path=str(data["project_file_path"]),
            render_script_path=str(data["render_script_path"]),
            frame_range_from=int(data["frame_range_from"]),
            frame_range_to=int(data["frame_range_to"]),
            wait_for_number_of_workers=int(data["wait_for_number_of_workers"]),
            frame_distribution_strategy=DistributionStrategy.from_dict(
                data["frame_distribution_strategy"]
            ),
            output_directory_path=str(data["output_directory_path"]),
            output_file_name_format=str(data["output_file_name_format"]),
            output_file_format=str(data["output_file_format"]),
            render_backend=data.get("render_backend"),
            # Raw value through to __post_init__'s normalization, so a
            # malformed tiles key gets the aggregated 'Invalid job' error
            # instead of a bare int() traceback here.
            tile_grid=data.get("tiles"),
            slo=data.get("slo"),
        )

    @classmethod
    def load_from_file(cls, path: str | Path) -> "BlenderJob":
        path = Path(path)
        if path.exists() and not path.is_file():
            raise ValueError(f"Path exists, but it is not a file: {path}")
        if not path.exists():
            raise FileNotFoundError(f"No such job file: {path}")
        with path.open("rb") as f:
            data = tomllib.load(f)
        job = cls.from_dict(data)
        if job.tile_grid is None:
            # TRC_TILE_GRID supplies a default grid at LOAD time only:
            # wire decoding must never consult the environment, or a
            # worker could reinterpret a job the master defined.
            from tpu_render_cluster.jobs.tiles import env_tile_grid

            grid = env_tile_grid()
            if grid is not None:
                job = cls.from_dict({**data, "tiles": list(grid)})
        return job
