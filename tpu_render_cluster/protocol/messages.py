"""The 14-message job protocol (+ the goodbye drain extension).

Wire format is the reference's externally-observable contract: a JSON text
frame ``{"message_type": "<tag>", "payload": {...}}`` (reference:
shared/src/messages/mod.rs:150-236) with the exact serde tags from the
reference's enum (including the asymmetric ``response_frame-queue-add`` tag,
shared/src/messages/mod.rs:171). Requests carry a random u64
``message_request_id``; responses echo it as ``message_request_context_id``
(shared/src/messages/utilities.rs:5-14, shared/src/messages/queue.rs:13-100).
``event_worker-goodbye`` is this repo's one NEW message (graceful drain);
every other extension rides as optional keys inside reference payloads —
``trace`` (causal context), the heartbeat metrics/clock fields, and
``job_id`` (the multi-job scheduler's submission id, PROTOCOL.md
§Multi-job scheduling).

Worker IDs are random u32s displayed as 8-hex
(shared/src/messages/handshake.rs:9-26).
"""

from __future__ import annotations

import json
import secrets
from dataclasses import dataclass
from typing import Any, ClassVar

from tpu_render_cluster.jobs.models import BlenderJob
from tpu_render_cluster.traces.worker_trace import WorkerTrace
from tpu_render_cluster.utils.timestamps import now_ts

# ---------------------------------------------------------------------------
# IDs

def generate_message_request_id() -> int:
    """Random u64 request id (reference: shared/src/messages/utilities.rs:11)."""
    return secrets.randbits(64)


def generate_worker_id() -> int:
    """Random u32 worker id (reference: shared/src/messages/handshake.rs:20)."""
    return secrets.randbits(32)


def generate_trace_id() -> int:
    """Random u64 trace id: one per job, shared by every frame's spans."""
    return secrets.randbits(64)


# ---------------------------------------------------------------------------
# Trace context (optional, beyond-reference)
#
# A (trace_id, span_id) pair rides protocol messages the same way the
# heartbeat metrics payload does: an OPTIONAL key that absent decodes to
# None and that reference-shaped peers (the C++ daemons) simply ignore.
# The master mints one span_id per frame ASSIGNMENT (a re-queued or stolen
# frame starts a fresh span chain) and the worker echoes the context on its
# rendering/finished events, so the two sides' Perfetto spans link up as
# flow arrows without any clock agreement.


@dataclass(frozen=True)
class TraceContext:
    """Causal link for one frame assignment: job trace id + assignment span."""

    trace_id: int
    span_id: int

    @classmethod
    def new(cls, trace_id: int) -> "TraceContext":
        return cls(trace_id=trace_id, span_id=secrets.randbits(64))

    @property
    def flow_id(self) -> str:
        """Perfetto flow-event id (string: u64s overflow JSON readers)."""
        return f"{self.span_id:016x}"

    def to_dict(self) -> dict[str, int]:
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "TraceContext":
        return cls(trace_id=int(data["trace_id"]), span_id=int(data["span_id"]))


def _trace_from_payload(payload: dict[str, Any]) -> TraceContext | None:
    """Decode the optional ``trace`` key (piggyback idiom: absent -> None)."""
    data = payload.get("trace")
    if data is None:
        return None
    if not isinstance(data, dict):
        raise ValueError("trace context must be an object")
    return TraceContext.from_dict(data)


def worker_id_to_string(worker_id: int) -> str:
    """Workers display as 8-hex (reference: shared/src/messages/handshake.rs:14-17)."""
    return f"{worker_id:08x}"


def _job_id_from_payload(payload: dict[str, Any]) -> str | None:
    """Decode the optional ``job_id`` key (piggyback idiom: absent -> None).

    Rides queue-add requests and their echo events when the master runs
    the multi-job scheduler (sched/), uniquely naming the job *submission*
    even across job-name reuse. Single-job masters never set it, so their
    wire traffic stays byte-identical to the reference.
    """
    job_id = payload.get("job_id")
    if job_id is None:
        return None
    if not isinstance(job_id, str):
        raise ValueError("job_id must be a string")
    return job_id


def _epoch_from_payload(payload: dict[str, Any]) -> int | None:
    """Decode the optional ``epoch`` key (piggyback idiom: absent -> None).

    The monotonic master-incarnation counter of the replicated control
    plane (PROTOCOL.md §Epoch fencing & failover): a ledger-backed master
    stamps its epoch on the handshake request and every queue-add, and
    (Python) workers echo it on their frame events, so a master that took
    over after a failover can refuse results belonging to a predecessor's
    assignments instead of silently applying them. Masters without a
    ledger never set it — their traffic stays byte-identical to the
    reference, and C++ peers route unmodified.
    """
    epoch = payload.get("epoch")
    if epoch is None:
        return None
    if isinstance(epoch, bool) or not isinstance(epoch, int):
        raise ValueError("epoch must be an integer")
    if epoch < 0:
        raise ValueError(f"epoch must be >= 0, got {epoch}")
    return epoch


def _tile_from_payload(payload: dict[str, Any]) -> int | None:
    """Decode the optional ``tile`` key (piggyback idiom: absent -> None).

    Rides queue add/remove requests and both frame-event echoes when the
    job splits frames into sub-frame tiles (PROTOCOL.md §Tile-sharded
    frames). Whole-frame jobs never set it — their traffic stays
    byte-identical to the reference, and C++ workers (which neither read
    nor echo the key) interoperate on whole-frame jobs unmodified.
    """
    tile = payload.get("tile")
    if tile is None:
        return None
    if isinstance(tile, bool) or not isinstance(tile, int):
        raise ValueError("tile must be an integer tile index")
    if tile < 0:
        raise ValueError(f"tile index must be >= 0, got {tile}")
    return tile


# ---------------------------------------------------------------------------
# Result-enum wire values

FRAME_QUEUE_ADD_RESULT_ADDED = "added-to-queue"
FRAME_QUEUE_ADD_RESULT_ERRORED = "errored"

FRAME_QUEUE_REMOVE_RESULT_REMOVED = "removed-from-queue"
FRAME_QUEUE_REMOVE_RESULT_ALREADY_RENDERING = "already-rendering"
FRAME_QUEUE_REMOVE_RESULT_ALREADY_FINISHED = "already-finished"
FRAME_QUEUE_REMOVE_RESULT_ERRORED = "errored"

FRAME_QUEUE_ITEM_FINISHED_OK = "ok"
FRAME_QUEUE_ITEM_FINISHED_ERRORED = "errored"

HANDSHAKE_TYPE_FIRST_CONNECTION = "first-connection"
HANDSHAKE_TYPE_RECONNECTING = "reconnecting"


def _result_to_dict(result: str, error_reason: str | None) -> dict[str, Any]:
    out: dict[str, Any] = {"result": result}
    if result == "errored":
        out["reason"] = error_reason or ""
    return out


def _result_from_dict(data: dict[str, Any]) -> tuple[str, str | None]:
    return str(data["result"]), data.get("reason")


# ---------------------------------------------------------------------------
# Message classes


class Message:
    """Base class; subclasses define ``type_name`` (the wire tag) and payload serde."""

    type_name: ClassVar[str]

    def to_payload(self) -> dict[str, Any]:  # pragma: no cover - overridden
        raise NotImplementedError

    @classmethod
    def from_payload(cls, payload: dict[str, Any]) -> "Message":  # pragma: no cover
        raise NotImplementedError


@dataclass(frozen=True)
class MasterHandshakeRequest(Message):
    """M→W (reference: shared/src/messages/handshake.rs:31-47)."""

    type_name: ClassVar[str] = "handshake_request"
    server_version: str
    # Optional master epoch (replicated control plane, piggyback idiom):
    # a reconnecting worker that sees a DIFFERENT epoch than the master it
    # lost knows it is talking to a new incarnation and re-announces as a
    # fresh session instead of replaying stale queue state into it.
    epoch: int | None = None

    def to_payload(self) -> dict[str, Any]:
        out: dict[str, Any] = {"server_version": self.server_version}
        if self.epoch is not None:
            out["epoch"] = self.epoch
        return out

    @classmethod
    def from_payload(cls, payload: dict[str, Any]) -> "MasterHandshakeRequest":
        return cls(
            server_version=str(payload["server_version"]),
            epoch=_epoch_from_payload(payload),
        )


@dataclass(frozen=True)
class WorkerHandshakeResponse(Message):
    """W→M (reference: shared/src/messages/handshake.rs:66-117)."""

    type_name: ClassVar[str] = "handshake_response"
    handshake_type: str  # "first-connection" | "reconnecting"
    worker_version: str
    worker_id: int

    def to_payload(self) -> dict[str, Any]:
        return {
            "handshake_type": self.handshake_type,
            "worker_version": self.worker_version,
            "worker_id": self.worker_id,
        }

    @classmethod
    def from_payload(cls, payload: dict[str, Any]) -> "WorkerHandshakeResponse":
        return cls(
            handshake_type=str(payload["handshake_type"]),
            worker_version=str(payload["worker_version"]),
            worker_id=int(payload["worker_id"]),
        )


@dataclass(frozen=True)
class MasterHandshakeAcknowledgement(Message):
    """M→W (reference: shared/src/messages/handshake.rs:139-153)."""

    type_name: ClassVar[str] = "handshake_acknowledgement"
    ok: bool

    def to_payload(self) -> dict[str, Any]:
        return {"ok": self.ok}

    @classmethod
    def from_payload(cls, payload: dict[str, Any]) -> "MasterHandshakeAcknowledgement":
        return cls(ok=bool(payload["ok"]))


@dataclass(frozen=True)
class MasterFrameQueueAddRequest(Message):
    """M→W: queue a frame; carries the full job (shared/src/messages/queue.rs:15-38)."""

    type_name: ClassVar[str] = "request_frame-queue_add"
    message_request_id: int
    job: BlenderJob
    frame_index: int
    # Optional causal context (beyond-reference, piggyback idiom): absent
    # on the wire decodes to None; the C++ worker ignores the extra key.
    trace: TraceContext | None = None
    # Optional scheduler job id (multi-job masters only, same idiom).
    job_id: str | None = None
    # Optional sub-frame tile index (tiled jobs only, same idiom).
    tile: int | None = None
    # Optional master epoch (ledger-backed masters only, same idiom): the
    # worker stamps its copy and echoes it on the frame's events, fencing
    # a pre-failover assignment's results out of the successor master.
    epoch: int | None = None

    @classmethod
    def new(
        cls,
        job: BlenderJob,
        frame_index: int,
        *,
        trace: TraceContext | None = None,
        job_id: str | None = None,
        tile: int | None = None,
        epoch: int | None = None,
    ) -> "MasterFrameQueueAddRequest":
        return cls(
            generate_message_request_id(), job, frame_index, trace, job_id,
            tile, epoch,
        )

    def to_payload(self) -> dict[str, Any]:
        out = {
            "message_request_id": self.message_request_id,
            "job": self.job.to_dict(),
            "frame_index": self.frame_index,
        }
        if self.trace is not None:
            out["trace"] = self.trace.to_dict()
        if self.job_id is not None:
            out["job_id"] = self.job_id
        if self.tile is not None:
            out["tile"] = self.tile
        if self.epoch is not None:
            out["epoch"] = self.epoch
        return out

    @classmethod
    def from_payload(cls, payload: dict[str, Any]) -> "MasterFrameQueueAddRequest":
        return cls(
            message_request_id=int(payload["message_request_id"]),
            job=BlenderJob.from_dict(payload["job"]),
            frame_index=int(payload["frame_index"]),
            trace=_trace_from_payload(payload),
            job_id=_job_id_from_payload(payload),
            tile=_tile_from_payload(payload),
            epoch=_epoch_from_payload(payload),
        )


@dataclass(frozen=True)
class WorkerFrameQueueAddResponse(Message):
    """W→M (shared/src/messages/queue.rs:61-100). Note the asymmetric wire tag."""

    type_name: ClassVar[str] = "response_frame-queue-add"
    message_request_context_id: int
    result: str
    error_reason: str | None = None

    @classmethod
    def new_ok(cls, request_id: int) -> "WorkerFrameQueueAddResponse":
        return cls(request_id, FRAME_QUEUE_ADD_RESULT_ADDED)

    @classmethod
    def new_errored(cls, request_id: int, reason: str) -> "WorkerFrameQueueAddResponse":
        return cls(request_id, FRAME_QUEUE_ADD_RESULT_ERRORED, reason)

    def to_payload(self) -> dict[str, Any]:
        return {
            "message_request_context_id": self.message_request_context_id,
            "result": _result_to_dict(self.result, self.error_reason),
        }

    @classmethod
    def from_payload(cls, payload: dict[str, Any]) -> "WorkerFrameQueueAddResponse":
        result, reason = _result_from_dict(payload["result"])
        return cls(int(payload["message_request_context_id"]), result, reason)


@dataclass(frozen=True)
class MasterFrameQueueRemoveRequest(Message):
    """M→W: un-queue (steal) a frame (shared/src/messages/queue.rs:123-146)."""

    type_name: ClassVar[str] = "request_frame-queue_remove"
    message_request_id: int
    job_name: str
    frame_index: int
    # Optional sub-frame tile index (piggyback idiom): a tiled steal or
    # preemption removes one TILE; whole-frame requests omit the key.
    tile: int | None = None

    @classmethod
    def new(
        cls, job_name: str, frame_index: int, *, tile: int | None = None
    ) -> "MasterFrameQueueRemoveRequest":
        return cls(generate_message_request_id(), job_name, frame_index, tile)

    def to_payload(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "message_request_id": self.message_request_id,
            "job_name": self.job_name,
            "frame_index": self.frame_index,
        }
        if self.tile is not None:
            out["tile"] = self.tile
        return out

    @classmethod
    def from_payload(cls, payload: dict[str, Any]) -> "MasterFrameQueueRemoveRequest":
        return cls(
            message_request_id=int(payload["message_request_id"]),
            job_name=str(payload["job_name"]),
            frame_index=int(payload["frame_index"]),
            tile=_tile_from_payload(payload),
        )


@dataclass(frozen=True)
class WorkerFrameQueueRemoveResponse(Message):
    """W→M (shared/src/messages/queue.rs:168-227)."""

    type_name: ClassVar[str] = "response_frame-queue_remove"
    message_request_context_id: int
    result: str
    error_reason: str | None = None

    @classmethod
    def new_with_result(
        cls, request_id: int, result: str, reason: str | None = None
    ) -> "WorkerFrameQueueRemoveResponse":
        return cls(request_id, result, reason)

    def to_payload(self) -> dict[str, Any]:
        return {
            "message_request_context_id": self.message_request_context_id,
            "result": _result_to_dict(self.result, self.error_reason),
        }

    @classmethod
    def from_payload(cls, payload: dict[str, Any]) -> "WorkerFrameQueueRemoveResponse":
        result, reason = _result_from_dict(payload["result"])
        return cls(int(payload["message_request_context_id"]), result, reason)


@dataclass(frozen=True)
class WorkerFrameQueueItemRenderingEvent(Message):
    """W→M: frame started rendering (shared/src/messages/queue.rs:255-274).

    The reference defines + handles this event but its worker never emits it
    (SURVEY.md §3.3); our worker does emit it, completing the protocol.
    """

    type_name: ClassVar[str] = "event_frame-queue_item-started-rendering"
    job_name: str
    frame_index: int
    # Echo of the queue-add request's optional trace context.
    trace: TraceContext | None = None
    # Echo of the queue-add request's optional scheduler job id.
    job_id: str | None = None
    # Echo of the queue-add request's optional tile index.
    tile: int | None = None
    # Echo of the queue-add request's optional master epoch (fencing).
    epoch: int | None = None

    def to_payload(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "job_name": self.job_name,
            "frame_index": self.frame_index,
        }
        if self.trace is not None:
            out["trace"] = self.trace.to_dict()
        if self.job_id is not None:
            out["job_id"] = self.job_id
        if self.tile is not None:
            out["tile"] = self.tile
        if self.epoch is not None:
            out["epoch"] = self.epoch
        return out

    @classmethod
    def from_payload(cls, payload: dict[str, Any]) -> "WorkerFrameQueueItemRenderingEvent":
        return cls(
            str(payload["job_name"]),
            int(payload["frame_index"]),
            trace=_trace_from_payload(payload),
            job_id=_job_id_from_payload(payload),
            tile=_tile_from_payload(payload),
            epoch=_epoch_from_payload(payload),
        )


@dataclass(frozen=True)
class WorkerFrameQueueItemFinishedEvent(Message):
    """W→M: frame finished (ok | errored) (shared/src/messages/queue.rs:299-343).

    Unlike the reference's worker (which swallows render errors —
    worker/src/rendering/queue.rs:169-174), ours reports errors so the
    master can reschedule instead of hanging.
    """

    type_name: ClassVar[str] = "event_frame-queue_item-finished"
    job_name: str
    frame_index: int
    result: str  # "ok" | "errored"
    error_reason: str | None = None
    # Echo of the queue-add request's optional trace context, so the
    # master can terminate the frame's flow without local bookkeeping.
    trace: TraceContext | None = None
    # Echo of the queue-add request's optional scheduler job id.
    job_id: str | None = None
    # Echo of the queue-add request's optional tile index: the master's
    # assembly ledger credits the finished TILE, not the whole frame.
    tile: int | None = None
    # Echo of the queue-add request's optional master epoch: a result
    # stamped with a predecessor master's epoch is refused (and counted)
    # by the successor instead of silently applied.
    epoch: int | None = None

    @classmethod
    def new_ok(
        cls,
        job_name: str,
        frame_index: int,
        *,
        trace: TraceContext | None = None,
        job_id: str | None = None,
        tile: int | None = None,
        epoch: int | None = None,
    ) -> "WorkerFrameQueueItemFinishedEvent":
        return cls(
            job_name, frame_index, FRAME_QUEUE_ITEM_FINISHED_OK, trace=trace,
            job_id=job_id, tile=tile, epoch=epoch,
        )

    @classmethod
    def new_errored(
        cls,
        job_name: str,
        frame_index: int,
        reason: str,
        *,
        trace: TraceContext | None = None,
        job_id: str | None = None,
        tile: int | None = None,
        epoch: int | None = None,
    ) -> "WorkerFrameQueueItemFinishedEvent":
        return cls(
            job_name, frame_index, FRAME_QUEUE_ITEM_FINISHED_ERRORED, reason,
            trace=trace, job_id=job_id, tile=tile, epoch=epoch,
        )

    def to_payload(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "job_name": self.job_name,
            "frame_index": self.frame_index,
            "result": _result_to_dict(self.result, self.error_reason),
        }
        if self.trace is not None:
            out["trace"] = self.trace.to_dict()
        if self.job_id is not None:
            out["job_id"] = self.job_id
        if self.tile is not None:
            out["tile"] = self.tile
        if self.epoch is not None:
            out["epoch"] = self.epoch
        return out

    @classmethod
    def from_payload(cls, payload: dict[str, Any]) -> "WorkerFrameQueueItemFinishedEvent":
        result, reason = _result_from_dict(payload["result"])
        return cls(
            str(payload["job_name"]),
            int(payload["frame_index"]),
            result,
            reason,
            trace=_trace_from_payload(payload),
            job_id=_job_id_from_payload(payload),
            tile=_tile_from_payload(payload),
            epoch=_epoch_from_payload(payload),
        )


@dataclass(frozen=True)
class MasterHeartbeatRequest(Message):
    """M→W ping with fractional unix timestamp (shared/src/messages/heartbeat.rs:12-31)."""

    type_name: ClassVar[str] = "request_heartbeat"
    request_time: float

    @classmethod
    def new_now(cls) -> "MasterHeartbeatRequest":
        return cls(request_time=now_ts())

    def to_payload(self) -> dict[str, Any]:
        return {"request_time": self.request_time}

    @classmethod
    def from_payload(cls, payload: dict[str, Any]) -> "MasterHeartbeatRequest":
        return cls(request_time=float(payload["request_time"]))


@dataclass(frozen=True)
class WorkerHeartbeatResponse(Message):
    """W→M pong (shared/src/messages/heartbeat.rs:52-66).

    Extensions over the reference's empty payload, all riding the same
    piggyback idiom (absent key decodes to ``None``; the C++ worker sends
    the reference's empty payload and the C++ master reads only
    ``message_type``, so both directions stay reference-compatible):

    - ``metrics`` — OPTIONAL compact metrics payload
      (``obs.registry.to_wire()`` shape) so the master can aggregate a
      live cluster-wide view with zero extra round-trips;
    - ``received_at`` / ``responded_at`` — OPTIONAL fractional-unix
      timestamps on the worker's clock. Together with the ping's
      ``request_time`` and the master's receive time they complete the
      NTP four-timestamp exchange the per-worker clock-offset estimator
      (``obs/clocksync.py``) feeds on;
    - ``echo_request_time`` — OPTIONAL echo of the ping's
      ``request_time``, correlating pong to ping. The reference's pongs
      are anonymous, which was fine while one missed pong evicted the
      worker; with pong-miss retries a stale pong could otherwise be
      taken for the retry's answer and feed the clock estimator a sample
      whose four timestamps span two different exchanges.
    """

    type_name: ClassVar[str] = "response_heartbeat"
    metrics: dict[str, Any] | None = None
    received_at: float | None = None
    responded_at: float | None = None
    echo_request_time: float | None = None

    def to_payload(self) -> dict[str, Any]:
        out: dict[str, Any] = {}
        if self.metrics is not None:
            out["metrics"] = self.metrics
        if self.received_at is not None:
            out["received_at"] = self.received_at
        if self.responded_at is not None:
            out["responded_at"] = self.responded_at
        if self.echo_request_time is not None:
            out["echo_request_time"] = self.echo_request_time
        return out

    @classmethod
    def from_payload(cls, payload: dict[str, Any]) -> "WorkerHeartbeatResponse":
        metrics = payload.get("metrics")
        if metrics is not None and not isinstance(metrics, dict):
            raise ValueError("heartbeat metrics payload must be an object")
        received_at = payload.get("received_at")
        responded_at = payload.get("responded_at")
        echo_request_time = payload.get("echo_request_time")
        return cls(
            metrics=metrics,
            received_at=None if received_at is None else float(received_at),
            responded_at=None if responded_at is None else float(responded_at),
            echo_request_time=(
                None if echo_request_time is None else float(echo_request_time)
            ),
        )


@dataclass(frozen=True)
class WorkerGoodbyeEvent(Message):
    """W→M: graceful departure (beyond-reference, drain protocol).

    Sent when a worker is asked to drain (SIGTERM, maintenance): it
    finishes the frame it is rendering, returns every still-queued frame
    index so the master can requeue them immediately — instead of paying
    a heartbeat-timeout eviction to discover the departure — and goes
    away. Reference-compatible by the piggyback rule: a C++ master may
    ignore the unknown message type (the socket death that follows takes
    the reference's eviction path instead).
    """

    type_name: ClassVar[str] = "event_worker-goodbye"
    reason: str = "drain"
    job_name: str | None = None
    returned_frames: tuple[int, ...] = ()
    # Optional tile indices aligned 1:1 with ``returned_frames`` (null for
    # whole-frame entries). Omitted entirely when every returned unit is a
    # whole frame, keeping untiled goodbyes byte-identical.
    returned_tiles: tuple[int | None, ...] | None = None

    def to_payload(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "reason": self.reason,
            "returned_frames": list(self.returned_frames),
        }
        if self.job_name is not None:
            out["job_name"] = self.job_name
        if self.returned_tiles is not None and any(
            t is not None for t in self.returned_tiles
        ):
            out["returned_tiles"] = list(self.returned_tiles)
        return out

    @classmethod
    def from_payload(cls, payload: dict[str, Any]) -> "WorkerGoodbyeEvent":
        frames = payload.get("returned_frames") or []
        if not isinstance(frames, list):
            raise ValueError("returned_frames must be a list")
        tiles = payload.get("returned_tiles")
        if tiles is not None:
            if not isinstance(tiles, list) or len(tiles) != len(frames):
                raise ValueError(
                    "returned_tiles must align 1:1 with returned_frames"
                )
            tiles = tuple(None if t is None else int(t) for t in tiles)
        job_name = payload.get("job_name")
        return cls(
            reason=str(payload.get("reason", "drain")),
            job_name=None if job_name is None else str(job_name),
            returned_frames=tuple(int(f) for f in frames),
            returned_tiles=tiles,
        )


@dataclass(frozen=True)
class MasterJobStartedEvent(Message):
    """M→W job-started broadcast (shared/src/messages/job.rs:11-25).

    Empty in the reference; this repo's master piggybacks the OPTIONAL job
    ``trace_id`` so every process stamps its spans with the same trace.
    """

    type_name: ClassVar[str] = "event_job-started"
    trace_id: int | None = None
    # Optional scheduler job id (multi-job masters announce one event per
    # ACTIVE job — late joiners get them all replayed at handshake time).
    job_id: str | None = None

    def to_payload(self) -> dict[str, Any]:
        out: dict[str, Any] = {}
        if self.trace_id is not None:
            out["trace_id"] = self.trace_id
        if self.job_id is not None:
            out["job_id"] = self.job_id
        return out

    @classmethod
    def from_payload(cls, payload: dict[str, Any]) -> "MasterJobStartedEvent":
        trace_id = payload.get("trace_id")
        return cls(
            trace_id=None if trace_id is None else int(trace_id),
            job_id=_job_id_from_payload(payload),
        )


@dataclass(frozen=True)
class MasterJobFinishedRequest(Message):
    """M→W: request the worker's trace (shared/src/messages/job.rs:48-67)."""

    type_name: ClassVar[str] = "request_job-finished"
    message_request_id: int

    @classmethod
    def new(cls) -> "MasterJobFinishedRequest":
        return cls(generate_message_request_id())

    def to_payload(self) -> dict[str, Any]:
        return {"message_request_id": self.message_request_id}

    @classmethod
    def from_payload(cls, payload: dict[str, Any]) -> "MasterJobFinishedRequest":
        return cls(message_request_id=int(payload["message_request_id"]))


@dataclass(frozen=True)
class WorkerJobFinishedResponse(Message):
    """W→M: the full WorkerTrace (shared/src/messages/job.rs:90-110).

    Piggyback extension: ``span_events`` optionally carries the worker's
    Chrome trace-event timeline (``{"process_name": ..., "events": [...]}``)
    so a multi-host master can assemble the merged cluster timeline without
    a separate collection RPC. Absent (the C++ worker, a version-skewed
    peer) decodes to ``None`` and the master simply omits that worker's row.
    """

    type_name: ClassVar[str] = "response_job-finished"
    message_request_context_id: int
    trace: WorkerTrace
    span_events: dict[str, Any] | None = None

    def to_payload(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "message_request_context_id": self.message_request_context_id,
            "trace": self.trace.to_dict(),
        }
        if self.span_events is not None:
            out["span_events"] = self.span_events
        return out

    @classmethod
    def from_payload(cls, payload: dict[str, Any]) -> "WorkerJobFinishedResponse":
        span_events = payload.get("span_events")
        if span_events is not None and not isinstance(span_events, dict):
            raise ValueError("span_events payload must be an object")
        return cls(
            message_request_context_id=int(payload["message_request_context_id"]),
            trace=WorkerTrace.from_dict(payload["trace"]),
            span_events=span_events,
        )


# ---------------------------------------------------------------------------
# Ledger streaming replication (PROTOCOL.md §Ledger streaming replication)
#
# Follower <-> primary traffic over the JSON-lines control-plane idiom —
# one ``encode_message`` envelope per line on a plain TCP socket, NOT the
# worker WebSocket. These tags never ride the reference worker protocol,
# but they use the same envelope + schema registry so the wire-schema
# lint covers the replication contract too.


@dataclass(frozen=True)
class ReplicationAttachRequest(Message):
    """F→P: attach (or re-attach) to the primary's record stream.

    ``last_seq`` is the highest *contiguous* sequence number durably in
    the follower's local replica (0 = empty). The primary answers with
    everything after it — via a snapshot when ``last_seq`` predates the
    primary's compaction floor. The optional ``epoch`` carries the newest
    master epoch the follower has durably observed: a primary whose own
    epoch is LOWER knows it has been deposed and must refuse the attach
    rather than stream a stale timeline.
    """

    type_name: ClassVar[str] = "request_replication-attach"
    message_request_id: int
    last_seq: int
    epoch: int | None = None
    follower_id: str | None = None

    @classmethod
    def new(
        cls, last_seq: int, *, epoch: int | None = None, follower_id: str | None = None
    ) -> "ReplicationAttachRequest":
        return cls(
            generate_message_request_id(),
            last_seq=last_seq,
            epoch=epoch,
            follower_id=follower_id,
        )

    def to_payload(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "message_request_id": self.message_request_id,
            "last_seq": self.last_seq,
        }
        if self.epoch is not None:
            out["epoch"] = self.epoch
        if self.follower_id is not None:
            out["follower_id"] = self.follower_id
        return out

    @classmethod
    def from_payload(cls, payload: dict[str, Any]) -> "ReplicationAttachRequest":
        last_seq = int(payload["last_seq"])
        if last_seq < 0:
            raise ValueError(f"last_seq must be >= 0, got {last_seq}")
        follower_id = payload.get("follower_id")
        return cls(
            message_request_id=int(payload["message_request_id"]),
            last_seq=last_seq,
            epoch=_epoch_from_payload(payload),
            follower_id=None if follower_id is None else str(follower_id),
        )


@dataclass(frozen=True)
class ReplicationAttachResponse(Message):
    """P→F: accept (stream follows) or refuse an attach.

    On accept: ``epoch`` is the primary's current epoch, ``primary_seq``
    its highest committed sequence number (the follower's initial lag
    baseline), and ``snapshot`` — present only when the follower's
    ``last_seq`` predates the compaction floor — a full ledger snapshot
    document to seed the replica before the record stream resumes. On
    refusal ``error`` says why and the connection closes; the follower
    counts the refusal and does NOT retry a stale-epoch one.
    """

    type_name: ClassVar[str] = "response_replication-attach"
    message_request_context_id: int
    epoch: int
    primary_seq: int
    snapshot: dict[str, Any] | None = None
    error: str | None = None

    def to_payload(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "message_request_context_id": self.message_request_context_id,
            "epoch": self.epoch,
            "primary_seq": self.primary_seq,
        }
        if self.snapshot is not None:
            out["snapshot"] = self.snapshot
        if self.error is not None:
            out["error"] = self.error
        return out

    @classmethod
    def from_payload(cls, payload: dict[str, Any]) -> "ReplicationAttachResponse":
        snapshot = payload.get("snapshot")
        if snapshot is not None and not isinstance(snapshot, dict):
            raise ValueError("snapshot payload must be an object")
        error = payload.get("error")
        return cls(
            message_request_context_id=int(payload["message_request_context_id"]),
            epoch=int(payload["epoch"]),
            primary_seq=int(payload["primary_seq"]),
            snapshot=snapshot,
            error=None if error is None else str(error),
        )


@dataclass(frozen=True)
class ReplicationRecordEvent(Message):
    """P→F: one committed ledger record.

    ``record`` is the exact dict the primary appended (``{"v", "seq",
    "type", "job", "ts", ...}``); ``seq`` duplicates ``record["seq"]`` at
    the envelope level so the follower's gap detector never has to trust
    a partially-validated body. Streamed in strict sequence order; a gap
    means the connection lost records and the follower must re-attach
    from its last contiguous sequence.
    """

    type_name: ClassVar[str] = "event_replication-record"
    seq: int
    record: dict[str, Any]

    def to_payload(self) -> dict[str, Any]:
        return {"seq": self.seq, "record": self.record}

    @classmethod
    def from_payload(cls, payload: dict[str, Any]) -> "ReplicationRecordEvent":
        record = payload["record"]
        if not isinstance(record, dict):
            raise ValueError("record must be an object")
        return cls(seq=int(payload["seq"]), record=record)


@dataclass(frozen=True)
class ReplicationAckEvent(Message):
    """F→P: cumulative acknowledgement — every record up to and including
    ``seq`` is durably on the follower's disk. Sent every
    ``TRC_HA_REPL_ACK_EVERY`` records (and on stream idle), not per
    record; the primary's per-follower lag gauge is derived from it."""

    type_name: ClassVar[str] = "event_replication-ack"
    seq: int

    def to_payload(self) -> dict[str, Any]:
        return {"seq": self.seq}

    @classmethod
    def from_payload(cls, payload: dict[str, Any]) -> "ReplicationAckEvent":
        return cls(seq=int(payload["seq"]))


@dataclass(frozen=True)
class MasterWorkerMigrateEvent(Message):
    """M→W: re-home to another shard master (beyond-reference, rebalance).

    The shard router's rebalancer asks a hot shard's master to shed a
    worker; the master picks one and sends this event. The worker treats
    it exactly like a drain — finish the in-flight unit, return queued
    frames via ``event_worker-goodbye`` (reason ``"migrate"``) — then
    reconnects to ``host``:``port`` with a FRESH first-connection
    announce instead of exiting. A reference worker ignores the unknown
    tag and simply stays put, so rebalancing degrades to a no-op rather
    than an error on mixed fleets.
    """

    type_name: ClassVar[str] = "event_worker-migrate"
    host: str
    port: int
    reason: str | None = None

    def to_payload(self) -> dict[str, Any]:
        out: dict[str, Any] = {"host": self.host, "port": self.port}
        if self.reason is not None:
            out["reason"] = self.reason
        return out

    @classmethod
    def from_payload(cls, payload: dict[str, Any]) -> "MasterWorkerMigrateEvent":
        port = int(payload["port"])
        if not (0 < port < 65536):
            raise ValueError(f"port must be 1..65535, got {port}")
        reason = payload.get("reason")
        return cls(
            host=str(payload["host"]),
            port=port,
            reason=None if reason is None else str(reason),
        )


# ---------------------------------------------------------------------------
# Envelope

ALL_MESSAGE_TYPES: tuple[type[Message], ...] = (
    MasterHandshakeRequest,
    WorkerHandshakeResponse,
    MasterHandshakeAcknowledgement,
    MasterFrameQueueAddRequest,
    WorkerFrameQueueAddResponse,
    MasterFrameQueueRemoveRequest,
    WorkerFrameQueueRemoveResponse,
    WorkerFrameQueueItemRenderingEvent,
    WorkerFrameQueueItemFinishedEvent,
    WorkerGoodbyeEvent,
    MasterHeartbeatRequest,
    WorkerHeartbeatResponse,
    MasterJobStartedEvent,
    MasterJobFinishedRequest,
    WorkerJobFinishedResponse,
    ReplicationAttachRequest,
    ReplicationAttachResponse,
    ReplicationRecordEvent,
    ReplicationAckEvent,
    MasterWorkerMigrateEvent,
)

_TYPE_REGISTRY: dict[str, type[Message]] = {m.type_name: m for m in ALL_MESSAGE_TYPES}


def encode_message(message: Message) -> str:
    """Serialise to the tagged JSON envelope (a WS text frame)."""
    return json.dumps(
        {"message_type": message.type_name, "payload": message.to_payload()},
        separators=(",", ":"),
    )


def decode_message(text: str | bytes) -> Message:
    """Parse a tagged JSON envelope back into a typed message."""
    if isinstance(text, bytes):
        text = text.decode("utf-8")
    try:
        data = json.loads(text)
    except json.JSONDecodeError as e:
        raise ValueError(f"Malformed message frame: {e}") from e
    if not isinstance(data, dict):
        raise ValueError(f"Message frame must be a JSON object, got {type(data).__name__}")
    tag = data.get("message_type")
    cls = _TYPE_REGISTRY.get(tag) if isinstance(tag, str) else None
    if cls is None:
        raise ValueError(f"Unknown message_type: {tag!r}")
    payload = data.get("payload") or {}
    if not isinstance(payload, dict):
        raise ValueError(f"Message payload must be a JSON object, got {type(payload).__name__}")
    try:
        return cls.from_payload(payload)
    except (KeyError, TypeError, ValueError) as e:
        raise ValueError(f"Invalid payload for {tag!r}: {e}") from e
