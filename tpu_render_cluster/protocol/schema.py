"""Declared wire schemas for the job protocol — the machine-checked contract.

One :class:`WireSchema` per message tag, stating which payload keys are
REQUIRED (always serialized, reference-compatible shape) and which are
OPTIONAL piggybacks (this repo's beyond-reference extensions). The
``wire-schema`` lint pass (``tpu_render_cluster/lint/wire_schema.py``)
cross-checks three things against this registry on every tier-1 run:

1. ``protocol/messages.py`` — each class's ``to_payload`` must assign
   every required key unconditionally and every optional key ONLY under
   a presence guard (the omitted-when-absent idiom: an absent optional
   key must keep the serialized frame byte-identical to the reference's,
   never appear as ``null`` or a default); ``from_payload`` must read
   required keys strictly and optional keys leniently (``.get``/helper).
2. PROTOCOL.md — the message table must list exactly these tags, and
   every optional key must be mentioned in its tag's row.
3. This registry itself — every ``type_name`` in ``ALL_MESSAGE_TYPES``
   has exactly one schema and vice versa.

The registry is data, deliberately separate from the message classes: a
new key added to a dataclass without a schema update (or vice versa) is
a lint failure, which is the point — the optional-key idiom held across
PRs 3/5/7/11 by convention only.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class WireSchema:
    """Payload contract for one wire tag."""

    tag: str
    direction: str  # "M->W" | "W->M"
    required: tuple[str, ...]
    optional: tuple[str, ...] = ()

    @property
    def keys(self) -> frozenset[str]:
        return frozenset(self.required) | frozenset(self.optional)


@dataclass(frozen=True)
class FrameSegments:
    """Constant/varying payload split for a preserialized wire tag.

    Tags listed in :data:`FRAME_SEGMENTS` may be encoded by a splice
    codec (``protocol/frames.py``) that serializes the CONSTANT keys
    once per cache generation and splices the VARYING keys per message.
    The split is a pure encoding strategy — the wire bytes must remain
    identical to ``encode_message``'s — but it is still contract: the
    two sets must exactly partition the tag's declared keys (required +
    optional), which the ``wire-schema`` lint enforces along with a
    PROTOCOL.md section documenting the split.
    """

    tag: str
    constant: tuple[str, ...]
    varying: tuple[str, ...]


FRAME_SEGMENTS: dict[str, FrameSegments] = {
    segments.tag: segments
    for segments in (
        FrameSegments(
            "request_frame-queue_add",
            constant=("job",),
            varying=(
                "message_request_id",
                "frame_index",
                "trace",
                "job_id",
                "tile",
                "epoch",
            ),
        ),
    )
}


WIRE_SCHEMAS: dict[str, WireSchema] = {
    schema.tag: schema
    for schema in (
        WireSchema(
            "handshake_request",
            "M->W",
            required=("server_version",),
            optional=("epoch",),
        ),
        WireSchema(
            "handshake_response",
            "W->M",
            required=("handshake_type", "worker_version", "worker_id"),
        ),
        WireSchema(
            "handshake_acknowledgement",
            "M->W",
            required=("ok",),
        ),
        WireSchema(
            "request_frame-queue_add",
            "M->W",
            required=("message_request_id", "job", "frame_index"),
            optional=("trace", "job_id", "tile", "epoch"),
        ),
        WireSchema(
            "response_frame-queue-add",
            "W->M",
            required=("message_request_context_id", "result"),
        ),
        WireSchema(
            "request_frame-queue_remove",
            "M->W",
            required=("message_request_id", "job_name", "frame_index"),
            optional=("tile",),
        ),
        WireSchema(
            "response_frame-queue_remove",
            "W->M",
            required=("message_request_context_id", "result"),
        ),
        WireSchema(
            "event_frame-queue_item-started-rendering",
            "W->M",
            required=("job_name", "frame_index"),
            optional=("trace", "job_id", "tile", "epoch"),
        ),
        WireSchema(
            "event_frame-queue_item-finished",
            "W->M",
            required=("job_name", "frame_index", "result"),
            optional=("trace", "job_id", "tile", "epoch"),
        ),
        WireSchema(
            "request_heartbeat",
            "M->W",
            required=("request_time",),
        ),
        WireSchema(
            "response_heartbeat",
            "W->M",
            required=(),
            optional=("metrics", "received_at", "responded_at", "echo_request_time"),
        ),
        WireSchema(
            "event_worker-goodbye",
            "W->M",
            required=("reason", "returned_frames"),
            optional=("job_name", "returned_tiles"),
        ),
        WireSchema(
            "event_job-started",
            "M->W",
            required=(),
            optional=("trace_id", "job_id"),
        ),
        WireSchema(
            "request_job-finished",
            "M->W",
            required=("message_request_id",),
        ),
        # -- ledger streaming replication (PROTOCOL.md §Ledger streaming
        # replication): follower <-> primary over the JSON-lines TCP idiom,
        # one envelope per line. Not part of the reference worker protocol
        # — both ends are this repo's — but declared here so the same
        # wire-schema lint guards the contract.
        WireSchema(
            "request_replication-attach",
            "F->P",
            required=("message_request_id", "last_seq"),
            optional=("epoch", "follower_id"),
        ),
        WireSchema(
            "response_replication-attach",
            "P->F",
            required=("message_request_context_id", "epoch", "primary_seq"),
            optional=("snapshot", "error"),
        ),
        WireSchema(
            "event_replication-record",
            "P->F",
            required=("seq", "record"),
        ),
        WireSchema(
            "event_replication-ack",
            "F->P",
            required=("seq",),
        ),
        WireSchema(
            "event_worker-migrate",
            "M->W",
            required=("host", "port"),
            optional=("reason",),
        ),
        WireSchema(
            "response_job-finished",
            "W->M",
            required=("message_request_context_id", "trace"),
            optional=("span_events",),
        ),
    )
}
