"""Preserialized dispatch frames: the queue-add splice codec.

A ``request_frame-queue_add`` frame is dominated by its ``job`` object —
the full job spec (scene path, output template, distribution strategy,
tile grid, SLO block) repeated verbatim on EVERY dispatch, re-encoded
through ``json.dumps`` each time even though it never changes for the
life of a submission. This module splits the frame along the segment
boundary declared in :mod:`tpu_render_cluster.protocol.schema`
(``FRAME_SEGMENTS``):

- the CONSTANT segment (``job``) is serialized once per (job
  generation, master epoch) and cached — a same-name resubmit is a new
  ``BlenderJob`` *object*, so the cache key is the job's identity, not
  its name: a stale generation's bytes can never leave the master — and
  an epoch bump (ledger failover) re-encodes too;
- the VARYING segment (request id, frame index, and the optional
  trace/job_id/tile/epoch piggybacks) is spliced around it as strings,
  reproducing ``encode_message``'s output BYTE-IDENTICALLY — same key
  order, same ``(",", ":")`` separators, same omitted-when-absent
  optional-key idiom — so workers, the wire-schema lint, and the
  byte-exact wirecost accounting cannot tell the paths apart
  (PROTOCOL.md: the split adds zero bytes on the wire).

``TRC_DISPATCH_FRAMES=encode`` restores the per-send ``encode_message``
path (the A/B baseline for ``bench.py --sched``); the default
``cached`` uses this codec. Splices are pure string joins of int
renderings (``str(int)`` is exactly ``json.dumps(int)``) plus one
``json.dumps`` for the ``job_id`` string (escaping).
"""

from __future__ import annotations

import json

from tpu_render_cluster.protocol import messages as pm
from tpu_render_cluster.utils.env import env_str

__all__ = ["DispatchFrameCache", "frames_cached"]

# Bound on distinct job names one endpoint caches: a long-lived service
# seeing an unbounded stream of unique names must not grow without
# limit; eviction is FIFO (re-dispatches of a live job re-fill in one
# constant-segment encode).
CACHE_CAPACITY = 64

_PREFIX = (
    '{"message_type":"request_frame-queue_add",'
    '"payload":{"message_request_id":'
)


def frames_cached() -> bool:
    """Consulted per send, so tests and A/B benches can flip it live."""
    return (env_str("TRC_DISPATCH_FRAMES", "cached") or "").strip() != "encode"


class DispatchFrameCache:
    """Per-endpoint cache of preserialized ``job`` segments + splicer.

    One instance per ``WorkerHandle`` (caches are cheap; sharing across
    handles would only save re-encoding the same job once per worker).
    ``constant_encodes`` / ``splices`` are test/diagnostic counters: a
    burst of N dispatches of one job generation must show exactly one
    constant encode and N splices.
    """

    def __init__(self) -> None:
        # job_name -> (job object, epoch, serialized job dict). The job
        # OBJECT is the generation key: comparison is by identity, so a
        # resubmitted (new) job under an old name misses and re-encodes,
        # and keeping the reference pinned means CPython cannot recycle
        # the id while the entry lives.
        self._cache: dict[str, tuple[object, int | None, str]] = {}
        self.constant_encodes = 0
        self.splices = 0

    def encode(self, request: "pm.MasterFrameQueueAddRequest") -> str:
        """Byte-identical replacement for ``encode_message(request)``."""
        job = request.job
        entry = self._cache.get(job.job_name)
        if (
            entry is not None
            and entry[0] is job
            and entry[1] == request.epoch
        ):
            job_json = entry[2]
        else:
            job_json = json.dumps(job.to_dict(), separators=(",", ":"))
            self._cache.pop(job.job_name, None)
            while len(self._cache) >= CACHE_CAPACITY:
                self._cache.pop(next(iter(self._cache)))
            self._cache[job.job_name] = (job, request.epoch, job_json)
            self.constant_encodes += 1
        self.splices += 1
        parts = [
            _PREFIX,
            str(request.message_request_id),
            ',"job":',
            job_json,
            ',"frame_index":',
            str(request.frame_index),
        ]
        trace = request.trace
        if trace is not None:
            parts += (
                ',"trace":{"trace_id":',
                str(trace.trace_id),
                ',"span_id":',
                str(trace.span_id),
                "}",
            )
        if request.job_id is not None:
            parts += (',"job_id":', json.dumps(request.job_id))
        if request.tile is not None:
            parts += (',"tile":', str(request.tile))
        if request.epoch is not None:
            parts += (',"epoch":', str(request.epoch))
        parts.append("}}")
        return "".join(parts)
