"""Incrementally maintained weighted-fair-queueing pick structure.

The legacy tick (``TRC_SCHED_TICK=scan``) rebuilds every job's share
inputs from scratch each tick: an O(frames) status scan per job for the
in-flight set, an O(in-flight) x cost-model predict per job for the load,
and an O(jobs) list rebuild per dispatch slot. This module replaces that
with the structure ROADMAP item 3 calls for: one entry per running job
holding its current WFQ key (``load / weight`` within a strict priority
class), kept in a lazy min-heap so the dispatch pick is a heap peek.

Entries change only when the underlying job state changes, and every
such event — unit queued/completed/evicted, steal, worker death
returning units, ledger replay — funnels through a
``ClusterManagerState`` transition, which bumps the state's ``version``
counter (master/state.py). The manager therefore resyncs exactly the
DIRTY jobs each tick (version mismatch), reading the O(1) maintained
counters and pricing only the job's in-flight units; a quiet job costs
nothing. Weight/priority are re-read on every resync, so a weight change
is just another dirty entry.

Heap discipline: entries are immutable once pushed; updating a job bumps
its entry version and pushes a fresh tuple, and stale tuples (version
mismatch, departed job, or no pending work) are popped lazily at peek
time — the classic indexed-priority-queue-by-invalidation, O(log n)
amortized per update.

Ordering matches ``fair_share.pick_job_to_dispatch`` exactly in exact
arithmetic: highest priority class first, smallest ``load/weight``
within it, ties broken by admission sequence (the scan breaks ties by
input order, which the manager feeds in admission order). The scan's
``_EPS`` tolerance means near-ties (keys differing by less than 1e-9)
may legitimately resolve to either job; the ``verify`` tick mode treats
exactly that window as an acceptable divergence and anything wider as a
bug.
"""

from __future__ import annotations

import heapq

from tpu_render_cluster.sched.fair_share import JobShareInput

__all__ = ["IncrementalWFQ"]


class _Entry:
    __slots__ = (
        "job_id",
        "weight",
        "priority",
        "seq",
        "in_flight",
        "pending",
        "cost",
        "entry_version",
        "state_version",
    )

    def __init__(self, job_id: str, seq: int) -> None:
        self.job_id = job_id
        self.seq = seq
        self.weight = 1.0
        self.priority = 0
        self.in_flight = 0
        self.pending = 0
        self.cost: float | None = None
        self.entry_version = 0
        self.state_version = -1

    @property
    def load(self) -> float:
        return self.cost if self.cost is not None else float(self.in_flight)

    @property
    def key(self) -> float:
        return self.load / self.weight


class IncrementalWFQ:
    """Per-job WFQ entries + a lazy min-heap over the runnable ones."""

    def __init__(self) -> None:
        # Insertion order == first-sync order == admission order: the
        # manager first syncs a job the tick after it is admitted, so
        # inputs() reproduces the scan path's input order without a sort.
        self._entries: dict[str, _Entry] = {}
        # (-priority, key, seq, job_id, entry_version)
        self._heap: list[tuple[float, float, int, str, int]] = []
        self._next_seq = 0

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, job_id: str) -> bool:
        return job_id in self._entries

    def job_ids(self) -> list[str]:
        return list(self._entries)

    def needs_sync(self, job_id: str, state_version: int, cost_on: bool) -> bool:
        """True when the job's entry is absent, behind the state's
        mutation counter, or metered in the wrong unit (the cost model
        just gained its first history, or metering was toggled)."""
        entry = self._entries.get(job_id)
        if entry is None or entry.state_version != state_version:
            return True
        return (entry.cost is not None) != cost_on

    def sync(
        self,
        job_id: str,
        *,
        weight: float,
        priority: int,
        in_flight: int,
        pending: int,
        cost: float | None,
        state_version: int,
    ) -> None:
        """Install/refresh one job's entry from its state of truth."""
        entry = self._entries.get(job_id)
        if entry is None:
            entry = _Entry(job_id, self._next_seq)
            self._next_seq += 1
            self._entries[job_id] = entry
        entry.weight = weight
        entry.priority = priority
        entry.in_flight = in_flight
        entry.pending = pending
        entry.cost = cost
        entry.state_version = state_version
        self._reindex(entry)

    def remove(self, job_id: str) -> None:
        # Its heap tuples die lazily at peek time.
        self._entries.pop(job_id, None)

    def _reindex(self, entry: _Entry) -> None:
        entry.entry_version += 1
        if entry.pending > 0:
            heapq.heappush(
                self._heap,
                (
                    -entry.priority,
                    entry.key,
                    entry.seq,
                    entry.job_id,
                    entry.entry_version,
                ),
            )

    # -- event updates (within one tick's dispatch loop) --------------------

    def on_dispatched(self, job_id: str, predicted_cost: float) -> None:
        """One unit of this job just left pending for a worker's queue.

        Keeps the entry pick-accurate between full resyncs: the state's
        own transition already bumped its version, so the next tick's
        sync re-reads the truth and absorbs any prediction drift.
        """
        entry = self._entries.get(job_id)
        if entry is None:
            return
        entry.in_flight += 1
        entry.pending = max(0, entry.pending - 1)
        if entry.cost is not None:
            entry.cost += predicted_cost
        self._reindex(entry)

    def on_dispatch_failed(self, job_id: str) -> None:
        """Mirror of the scan path's failure bookkeeping: the claimed
        unit did not land (worker died mid-RPC, cancel raced, or the
        pending pool emptied under us) — stop offering it this tick; the
        next sync restores the true count."""
        entry = self._entries.get(job_id)
        if entry is None:
            return
        entry.pending = max(0, entry.pending - 1)
        self._reindex(entry)

    # -- picks ---------------------------------------------------------------

    def pick_dispatch(self) -> str | None:
        """The job the next free slot should serve — a lazy heap peek."""
        while self._heap:
            neg_priority, key, seq, job_id, entry_version = self._heap[0]
            entry = self._entries.get(job_id)
            if (
                entry is None
                or entry.entry_version != entry_version
                or entry.pending <= 0
            ):
                heapq.heappop(self._heap)
                continue
            return job_id
        return None

    def key_of(self, job_id: str) -> tuple[int, float] | None:
        """(priority, load/weight) of one entry — verify-mode forensics."""
        entry = self._entries.get(job_id)
        if entry is None:
            return None
        return entry.priority, entry.key

    def inputs(self) -> list[JobShareInput]:
        """Share inputs for targets/accounting/preemption, admission
        order, O(jobs) with no frame scans or predict calls."""
        return [
            JobShareInput(
                job_id=entry.job_id,
                weight=entry.weight,
                priority=entry.priority,
                in_flight=entry.in_flight,
                pending=entry.pending,
                in_flight_cost=entry.cost,
            )
            for entry in self._entries.values()
        ]
