"""Scheduler-facing job models: submissions and their lifecycle records.

A ``JobSpec`` is what a client submits: the reference ``BlenderJob`` TOML
payload plus the two scheduling knobs the reference never had — a
``weight`` (the job's fair share of in-flight frame slots relative to its
priority-class peers) and an integer ``priority`` class (strictly higher
classes are served first; weighted fair-share applies WITHIN a class).

A ``JobRun`` is the master-side lifecycle record of one submission:
``queued -> running -> finished | cancelled``, with the per-job frame
table (``ClusterManagerState``) attached at admission, plus the
time-weighted share accounting the acceptance criteria (achieved vs.
target share over the multi-job overlap window) and the ``sched`` section
of ``statistics.json`` are computed from.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any

from tpu_render_cluster.jobs.models import BlenderJob
from tpu_render_cluster.master.state import ClusterManagerState

JOB_QUEUED = "queued"
JOB_RUNNING = "running"
JOB_FINISHED = "finished"
JOB_CANCELLED = "cancelled"

JOB_STATES = (JOB_QUEUED, JOB_RUNNING, JOB_FINISHED, JOB_CANCELLED)


@dataclass(frozen=True)
class JobSpec:
    """One submission: the job payload + its scheduling parameters."""

    job: BlenderJob
    weight: float = 1.0
    priority: int = 0

    def __post_init__(self) -> None:
        if not math.isfinite(self.weight) or self.weight <= 0.0:
            raise ValueError(f"weight must be a positive finite number, got {self.weight!r}")
        if not isinstance(self.priority, int):
            raise ValueError(f"priority must be an integer, got {self.priority!r}")

    def to_dict(self) -> dict[str, Any]:
        return {
            "job": self.job.to_dict(),
            "weight": self.weight,
            "priority": self.priority,
        }

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "JobSpec":
        if "job" not in data:
            raise ValueError("job spec must carry a 'job' object")
        return cls(
            job=BlenderJob.from_dict(data["job"]),
            weight=float(data.get("weight", 1.0)),
            priority=int(data.get("priority", 0)),
        )


@dataclass
class JobRun:
    """Lifecycle record of one submission on the scheduler."""

    job_id: str
    spec: JobSpec
    submitted_at: float
    status: str = JOB_QUEUED
    admitted_at: float | None = None
    finished_at: float | None = None
    # Per-job frame table; attached at admission, kept after the job ends
    # (frozen) so late worker events resolve to "defunct" instead of
    # aliasing a newer job.
    state: ClusterManagerState | None = None
    preemptions: int = 0
    # Time-weighted share accounting over the MULTI-JOB OVERLAP window
    # (ticks during which >= 2 jobs were running): integrals of this job's
    # in-flight count, the cluster-wide in-flight total, and this job's
    # target share, plus the window's length. Achieved share is
    # in_flight integral / total integral; target share is its integral
    # over the window length.
    overlap_in_flight_integral: float = 0.0
    overlap_total_integral: float = 0.0
    overlap_target_integral: float = 0.0
    overlap_seconds: float = 0.0
    last_target_share: float = 0.0
    extras: dict[str, Any] = field(default_factory=dict)

    @property
    def job_name(self) -> str:
        return self.spec.job.job_name

    def is_active(self) -> bool:
        return self.status in (JOB_QUEUED, JOB_RUNNING)

    def admission_wait_seconds(self) -> float | None:
        if self.admitted_at is None:
            return None
        return self.admitted_at - self.submitted_at

    def makespan_seconds(self) -> float | None:
        """Admission to completion (None until the job ends, and for
        cancelled jobs that never ran)."""
        if self.admitted_at is None or self.finished_at is None:
            return None
        return self.finished_at - self.admitted_at

    def achieved_share(self) -> float | None:
        """This job's realized fraction of in-flight slots over the
        overlap window (None when the job never overlapped another)."""
        if self.overlap_total_integral <= 0.0:
            return None
        return self.overlap_in_flight_integral / self.overlap_total_integral

    def target_share(self) -> float | None:
        """Mean fair-share target over the same overlap window."""
        if self.overlap_seconds <= 0.0:
            return None
        return self.overlap_target_integral / self.overlap_seconds

    def view(self) -> dict[str, Any]:
        """Live JSON view (cluster_view 'jobs' section / control 'status')."""
        from tpu_render_cluster.master.cluster import job_state_view

        out: dict[str, Any] = {
            "job_id": self.job_id,
            "job_name": self.job_name,
            "weight": self.spec.weight,
            "priority": self.spec.priority,
            "status": self.status,
            "submitted_at": self.submitted_at,
            "admitted_at": self.admitted_at,
            "finished_at": self.finished_at,
            "admission_wait_seconds": self.admission_wait_seconds(),
            "makespan_seconds": self.makespan_seconds(),
            "preemptions": self.preemptions,
            "share": {
                "target": self.target_share(),
                "achieved": self.achieved_share(),
                "overlap_seconds": self.overlap_seconds,
                "last_target": self.last_target_share,
            },
        }
        if self.state is not None:
            out.update(job_state_view(self.state))
        else:
            out.update(
                {
                    "frames_total": 0,
                    "frames_finished": 0,
                    "frames_pending": 0,
                    "frames_in_flight": 0,
                }
            )
        return out
