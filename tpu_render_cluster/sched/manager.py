"""The multi-job scheduler: admission, fair-share dispatch, preemption.

``JobManager`` turns the master into a long-running service. It reuses
the whole single-job stack — the accepting server, 3-step handshake,
heartbeats, worker handles, eviction, drain, the exactly-once result
ledger — by subclassing ``ClusterManager`` in SERVICE mode (``job=None``)
and overriding the two multi-job hooks:

- ``_state_for_job``: worker events route to the owning job's frame table
  by the reference ``job_name`` field every event already carries (so C++
  workers that echo no ``job_id`` piggyback still route correctly);
- ``_active_job_announcements``: late-joining workers get one
  ``event_job-started`` replay per ACTIVE job.

Scheduling model (sched/fair_share.py): jobs are admitted from a queue
(priority order, capped by ``TRC_SCHED_MAX_ACTIVE_JOBS`` and each job's
worker barrier), then one dispatch loop multiplexes every running job
over the shared worker pool — per tick, each worker below its target
queue size receives the next frame of the runnable job with the smallest
``in_flight / weight`` (weighted fair queueing), and an over-share job is
preempted (its newest not-yet-rendering frame unqueued back to its own
pending pool, via the same frame-queue-remove RPC steals use) when
another job is starved by at least a whole slot.

Lifecycle API (``submit`` / ``job_status`` / ``cancel_job`` /
``request_drain``) is exposed over a JSON-lines control socket
(sched/control.py) consumed by ``python -m tpu_render_cluster.sched.submit``
and the master CLI's ``serve`` subcommand.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Any

from tpu_render_cluster.master.cluster import ClusterManager
from tpu_render_cluster.master.state import ClusterManagerState, FrameStatus
from tpu_render_cluster.master.strategies import (
    dispatch_one_pending,
    preempt_frame,
)
from tpu_render_cluster.master.worker_handle import WorkerHandle
from tpu_render_cluster.obs import MetricsRegistry, Tracer
from tpu_render_cluster.sched import fair_share
from tpu_render_cluster.sched.tickprof import TickProfiler
from tpu_render_cluster.sched.models import (
    JOB_CANCELLED,
    JOB_FINISHED,
    JOB_QUEUED,
    JOB_RUNNING,
    JobRun,
    JobSpec,
)
from tpu_render_cluster.sched.wfq import IncrementalWFQ
from tpu_render_cluster.traces.worker_trace import WorkerTrace
from tpu_render_cluster.utils.env import env_float, env_int, env_str

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class SchedulerConfig:
    """Tuning knobs, each with a ``TRC_SCHED_*`` environment override."""

    # Dispatch/admission tick. The single-job strategies tick at 50 ms
    # (reference: strategies.rs); the service loop matches.
    tick_seconds: float = 0.05
    # In-flight frame slots per live worker (the eager-naive-coarse
    # "target queue size" generalized to the whole service).
    target_queue_size: int = 2
    # Concurrently RUNNING jobs; further submissions wait in admission.
    max_active_jobs: int = 4
    # Master-side preemption of over-share jobs (fair_share.pick_preemption).
    preemption: bool = True
    max_preemptions_per_tick: int = 1
    # While DRAINING with nothing running, queued jobs whose worker
    # barrier exceeds the live pool are cancelled after this grace (late
    # worker connects get that long to satisfy the barrier); without it a
    # drained service would park forever on an unadmittable job.
    drain_barrier_grace_seconds: float = 10.0
    # Tick pick structure (sched/wfq.py): "heap" keeps per-job WFQ keys
    # in an incrementally synced priority queue (dispatch pick = heap
    # peek, share resync only for jobs whose state changed); "scan" is
    # the legacy full-rescan path kept as fallback and A/B baseline;
    # "verify" runs both and asserts every pick agrees (debug — it also
    # pins load metering to unit counts, the regime where heap-vs-scan
    # equivalence is exact rather than within the scan's tie tolerance).
    tick_mode: str = "heap"

    @classmethod
    def from_env(cls) -> "SchedulerConfig":
        tick_mode = (env_str("TRC_SCHED_TICK", cls.tick_mode) or "").strip()
        if tick_mode not in ("heap", "scan", "verify"):
            logger.warning(
                "Ignoring unknown TRC_SCHED_TICK=%r; using %r",
                tick_mode, cls.tick_mode,
            )
            tick_mode = cls.tick_mode
        return cls(
            tick_seconds=env_float("TRC_SCHED_TICK_SECONDS", cls.tick_seconds),
            target_queue_size=env_int(
                "TRC_SCHED_TARGET_QUEUE_SIZE", cls.target_queue_size
            ),
            max_active_jobs=env_int(
                "TRC_SCHED_MAX_ACTIVE_JOBS", cls.max_active_jobs
            ),
            preemption=env_int("TRC_SCHED_PREEMPTION", 1) != 0,
            max_preemptions_per_tick=env_int(
                "TRC_SCHED_MAX_PREEMPTIONS_PER_TICK", cls.max_preemptions_per_tick
            ),
            drain_barrier_grace_seconds=env_float(
                "TRC_SCHED_DRAIN_GRACE_SECONDS", cls.drain_barrier_grace_seconds
            ),
            tick_mode=tick_mode,
        )


class JobManager(ClusterManager):
    """Long-running multi-job master over one shared worker pool."""

    def __init__(
        self,
        host: str,
        port: int,
        *,
        config: SchedulerConfig | None = None,
        metrics: MetricsRegistry | None = None,
        span_tracer: Tracer | None = None,
        metrics_snapshot_path: str | Path | None = None,
        dispatch_delay_fn=None,
        output_base_directory: str | Path | None = None,
        telemetry_port: int | None = None,
        ledger=None,
    ) -> None:
        super().__init__(
            host,
            port,
            None,  # service mode: no single job, per-job states at admission
            metrics=metrics,
            span_tracer=span_tracer,
            metrics_snapshot_path=metrics_snapshot_path,
            dispatch_delay_fn=dispatch_delay_fn,
            output_base_directory=output_base_directory,
            telemetry_port=telemetry_port,
            ledger=ledger,
        )
        self.config = config if config is not None else SchedulerConfig.from_env()
        self.tickprof = TickProfiler(
            self.metrics,
            self.span_tracer,
            tick_budget_seconds=self.config.tick_seconds,
            flightrec=self.flightrec,
        )
        # Incremental WFQ pick structure (heap/verify tick modes): synced
        # per tick for DIRTY jobs only (state.version mismatch), so the
        # share_scan phase is O(changed jobs), not O(jobs x frames).
        self._wfq = IncrementalWFQ()
        self._runs: dict[str, JobRun] = {}  # job_id -> run, submit order
        self._admission: list[str] = []  # queued job_ids, submit order
        self._running: list[str] = []  # running job_ids, admission order
        self._active_by_name: dict[str, JobRun] = {}
        self._draining = False
        self._cancelling: set[str] = set()
        self._drain_stuck_since: float | None = None
        self._job_seq = 0
        self._started_serving = time.time()

    # -- ClusterManager hooks -------------------------------------------------

    def _state_for_job(self, job_name: str | None) -> ClusterManagerState | None:
        if job_name is None:
            return None
        run = self._active_by_name.get(job_name)
        return run.state if run is not None else None

    def _job_for_name(self, job_name: str | None):
        """Resolve an ACTIVE job for the cost model (scene key + tile
        grid); a defunct job's late observations price as the default
        scene — still useful worker-speed signal."""
        if job_name is None:
            return None
        run = self._active_by_name.get(job_name)
        return run.spec.job if run is not None else None

    def _active_job_announcements(self) -> list[tuple[int | None, str | None]]:
        out: list[tuple[int | None, str | None]] = []
        for job_id in self._running:
            run = self._runs[job_id]
            if run.state is not None:
                out.append((run.state.trace_id, run.job_id))
        return out

    def _jobs_view(self) -> dict:
        return {job_id: run.view() for job_id, run in self._runs.items()}

    # -- lifecycle API --------------------------------------------------------

    def submit(self, spec: JobSpec) -> str:
        """Queue one submission; returns its job_id. Raises on duplicate
        active job names (the wire protocol routes results by job_name,
        so two live jobs must never share one) and when draining."""
        if self._draining:
            raise RuntimeError("Scheduler is draining; not accepting jobs.")
        name = spec.job.job_name
        if name in self._active_by_name or any(
            self._runs[job_id].job_name == name for job_id in self._admission
        ):
            raise ValueError(
                f"A job named {name!r} is already queued or running; "
                "job names must be unique among active jobs."
            )
        self._job_seq += 1
        job_id = f"job-{self._job_seq:04d}"
        run = JobRun(job_id=job_id, spec=spec, submitted_at=time.time())
        self._runs[job_id] = run
        self._admission.append(job_id)
        self.metrics.counter(
            "sched_jobs_submitted_total", "Jobs submitted to the scheduler"
        ).inc()
        self.span_tracer.instant(
            "job submitted",
            cat="sched",
            track=f"job {job_id}",
            args={"job_id": job_id, "job_name": name, "weight": spec.weight,
                  "priority": spec.priority},
        )
        logger.info(
            "Job %s submitted: %r (weight=%g, priority=%d, %d frames).",
            job_id, name, spec.weight, spec.priority, spec.job.frame_count(),
        )
        return job_id

    def job_status(self, job_id: str) -> dict[str, Any] | None:
        run = self._runs.get(job_id)
        return run.view() if run is not None else None

    def scheduler_view(self) -> dict[str, Any]:
        """The ``sched`` section of the metrics snapshot / control status."""
        return {
            "draining": self._draining,
            "admission_queue": list(self._admission),
            "running": list(self._running),
            "total_slots": self._total_slots(),
            "rebalance": self.rebalance_view(),
            "jobs": {job_id: run.view() for job_id, run in self._runs.items()},
        }

    def rebalance_view(self) -> dict[str, Any]:
        """This shard's load summary, as the router's rebalancer consumes
        it (sched/rebalance.py): backlog in units, the cost model's
        predicted in-flight seconds (None until the model has history —
        commensurable with ``_share_inputs``'s fallback), and live
        workers. Queued-but-unadmitted jobs count their whole frame
        table; they are backlog this shard owns just as much as pending
        units of running jobs."""
        queue_depth = 0
        in_flight_cost: float | None = None
        for job_id in self._running:
            run = self._runs[job_id]
            assert run.state is not None
            queue_depth += run.state.pending_count() + run.state.in_flight_count()
            cost = self._in_flight_cost(run)
            if cost is not None:
                in_flight_cost = (in_flight_cost or 0.0) + cost
        for job_id in self._admission:
            queue_depth += self._runs[job_id].spec.job.frame_count()
        return {
            "queue_depth": queue_depth,
            "in_flight_cost_seconds": in_flight_cost,
            "workers": len(self.live_workers()),
        }

    async def migrate_workers(
        self, count: int, host: str, port: int, *, reason: str | None = None
    ) -> int:
        """Shed up to ``count`` live workers toward another shard master
        (the router's rebalance move, and its drain-a-dead-shard's-load
        primitive). Workers with the least queued work go first — their
        goodbye returns the fewest frames to this shard's pool — and each
        departs via the graceful migrate-goodbye path, so nothing is lost
        mid-move. Returns how many migrate events were actually sent."""
        workers = sorted(
            self.live_workers(), key=lambda w: len(w.queue.all_frames())
        )
        moved = 0
        for worker in workers[: max(0, int(count))]:
            try:
                await worker.send_migrate(host, port, reason=reason)
            except Exception as e:  # noqa: BLE001 - worker failure mid-send
                logger.warning(
                    "Migrate of worker %08x to %s:%d failed: %s",
                    worker.worker_id, host, port, e,
                )
                continue
            moved += 1
            self.metrics.counter(
                "master_worker_migrate_requests_total",
                "Migrate events sent to workers (shard rebalancing)",
            ).inc()
        return moved

    def cluster_view(self) -> dict:
        view = super().cluster_view()
        view["sched"] = self.scheduler_view()
        return view

    def timeline_other_data(self) -> dict | None:
        """Map the Perfetto ``job job-NNNN`` tracks back to submissions."""
        return {
            "sched_jobs": {
                job_id: {
                    "job_name": run.job_name,
                    "weight": run.spec.weight,
                    "priority": run.spec.priority,
                    "status": run.status,
                    "makespan_seconds": run.makespan_seconds(),
                    "preemptions": run.preemptions,
                }
                for job_id, run in self._runs.items()
            }
        }

    async def cancel_job(self, job_id: str) -> bool:
        """Cancel a queued or running job.

        A running job's not-yet-rendering frames are unqueued from every
        worker (the steal RPC's removal half), frames mid-render finish on
        the worker but their results resolve to a defunct job and are
        accounted as stale, and the job's name is released — the pool's
        slots go back to the remaining jobs with no ghost assignments.
        """
        run = self._runs.get(job_id)
        if (
            run is None
            or run.status in (JOB_FINISHED, JOB_CANCELLED)
            or job_id in self._cancelling
        ):
            return False
        if run.status == JOB_QUEUED:
            self._admission.remove(job_id)
            self._finish_run(run, JOB_CANCELLED, time.time())
            return True
        self._cancelling.add(job_id)
        try:
            # RUNNING: let the job's in-flight assembly stitches land
            # BEFORE its name is released — a same-name resubmit must
            # not race the old stitcher (reading a mixed tile set,
            # unlinking the new job's tile files) on the shared output
            # path. The await window is re-entry-safe via _cancelling.
            await self.assembly.drain_job(run.job_name)
            now = time.time()
            # Deactivate so in-flight events/dispatches resolve to
            # "defunct job" instead of mutating the frozen frame table.
            self._running.remove(job_id)
            self._wfq.remove(job_id)
            self._active_by_name.pop(run.job_name, None)
            self._finish_run(run, JOB_CANCELLED, now)
            for worker in self.live_workers():
                for frame in worker.queue.frames_for_job(run.job_name):
                    if frame.is_rendering:
                        continue  # its finished event will sweep the mirror
                    try:
                        await worker.unqueue_frame(run.job_name, frame.unit)
                    except Exception as e:  # noqa: BLE001 - worker failure mid-RPC
                        logger.warning(
                            "Cancel of %s: unqueue of unit %s on %08x failed: %s",
                            job_id, frame.unit.label, worker.worker_id, e,
                        )
            return True
        finally:
            self._cancelling.discard(job_id)

    def request_drain(self) -> None:
        """Stop admitting NEW submissions; serve() returns once every
        already-accepted job has finished (or been cancelled)."""
        self._draining = True

    # -- service loop ---------------------------------------------------------

    async def serve(self) -> list[tuple[str, WorkerTrace]]:
        """Bind, run the scheduler until drained, collect worker traces."""
        await self._bind_server()
        try:
            try:
                await self._scheduler_loop()
            finally:
                # Tiled jobs: stitches scheduled by the last finished
                # events may still be in flight when the loop drains —
                # or when it RAISES; either way they must land, not be
                # destroyed pending at teardown.
                await self.assembly.drain()
            with self.span_tracer.span(
                "collect traces", cat="master", track="job"
            ):
                worker_traces = await self._collect_worker_traces()
            return worker_traces
        finally:
            await self._shutdown_server()

    async def _scheduler_loop(self) -> None:
        last = time.time()
        while not self.cancellation.is_cancelled():
            now = time.time()
            dt, last = now - last, now
            await self._admit_ready_jobs(now)
            self._finalize_finished_jobs(now)
            # SLO tick inline (the single-job master runs a sidecar task
            # instead): window-slide recoveries and deadline breaches
            # surface even for jobs whose result stream has stalled.
            self.slo.tick(now)
            # A job whose unit exhausted its error budget (deterministic
            # render failure — worker_handle sets failed_reason) must not
            # spin redispatch forever: cancel it, releasing the pool.
            for job_id in list(self._running):
                run = self._runs[job_id]
                if run.state is not None and run.state.failed_reason:
                    logger.error(
                        "Job %s failed: %s — cancelling.",
                        job_id,
                        run.state.failed_reason,
                    )
                    # Flight-recorder seam: dump the window leading up to
                    # the failure before the cancel sweeps its state.
                    from tpu_render_cluster.obs.flightrec import (
                        TRIGGER_JOB_FAILURE,
                    )

                    self.flightrec.trigger(
                        TRIGGER_JOB_FAILURE,
                        {
                            "job_id": job_id,
                            "job": run.job_name,
                            "reason": run.state.failed_reason,
                        },
                    )
                    await self.cancel_job(job_id)
            if self._draining and not self._running and self._admission:
                # Liveness under drain: a queued job whose worker barrier
                # exceeds the live pool — with nothing running whose
                # completion could change the picture — would park the
                # service forever. Give late-connecting workers a grace
                # window (the harness submits and drains before its
                # workers even finish their handshakes), then cancel the
                # unadmittable leftovers loudly: the operator asked to
                # wind down.
                if self._drain_stuck_since is None:
                    self._drain_stuck_since = now
                elif (
                    now - self._drain_stuck_since
                    >= self.config.drain_barrier_grace_seconds
                ):
                    self._cancel_unadmittable_queued_jobs(now)
            else:
                self._drain_stuck_since = None
            if self._draining and not self._admission and not self._running:
                return
            if self._running:
                self.tickprof.begin_tick()
                # Fold fresh completion observations into the shared cost
                # model first: this tick's WFQ pick and speculation
                # decisions price off the newest evidence.
                with self.tickprof.phase("pricing"):
                    self.cost_service.ingest(
                        self.live_workers(), self._job_for_name
                    )
                with self.tickprof.phase("share_scan"):
                    inputs = self._tick_inputs()
                with self.tickprof.phase("fair_share"):
                    targets = self._compute_targets(inputs)
                    self._account_shares(dt, targets, inputs)
                with self.tickprof.phase("dispatch"):
                    await self._dispatch_tick(inputs)
                if self.config.preemption:
                    with self.tickprof.phase("preempt"):
                        await self._preempt_tick()
                if self.speculation.config.enabled:
                    # Tail hedging per running job AFTER dispatch: an idle
                    # worker only receives a speculative twin when no
                    # pending work exists for it (maybe_launch gates on
                    # the job's own pool; the dispatch pass above already
                    # consumed every globally-runnable frame this tick).
                    with self.tickprof.phase("speculation"):
                        workers = self.live_workers()
                        for job_id in list(self._running):
                            run = self._runs[job_id]
                            if run.state is not None:
                                await self.speculation.tick(
                                    run.spec.job,
                                    run.state,
                                    workers,
                                    job_id=job_id,
                                )
                self._finalize_finished_jobs(time.time())
                self.tickprof.end_tick()
            await asyncio.sleep(self.config.tick_seconds)

    def _cancel_unadmittable_queued_jobs(self, now: float) -> None:
        live = len(self.live_workers())
        for job_id in list(self._admission):
            run = self._runs[job_id]
            if run.spec.job.wait_for_number_of_workers > live:
                logger.warning(
                    "Drain: cancelling queued job %s (%r) — its worker "
                    "barrier (%d) exceeds the live pool (%d) and nothing "
                    "is running that could change that.",
                    job_id,
                    run.job_name,
                    run.spec.job.wait_for_number_of_workers,
                    live,
                )
                self._admission.remove(job_id)
                self._finish_run(run, JOB_CANCELLED, now)

    # -- admission ------------------------------------------------------------

    def _admission_order(self) -> list[str]:
        """Queued job_ids, highest priority first, submit order within."""
        return sorted(
            self._admission,
            key=lambda job_id: (-self._runs[job_id].spec.priority, job_id),
        )

    async def _admit_ready_jobs(self, now: float) -> None:
        live = len(self.live_workers())
        progressed = True
        while progressed:
            progressed = False
            for job_id in self._admission_order():
                if len(self._running) >= self.config.max_active_jobs:
                    return
                run = self._runs[job_id]
                if run.spec.job.wait_for_number_of_workers > live:
                    continue  # its worker barrier is not met yet
                await self._admit(run, now)
                progressed = True
                break

    async def _admit(self, run: JobRun, now: float) -> None:
        self._admission.remove(run.job_id)
        run.state = ClusterManagerState(run.spec.job)
        run.state.sched_job_id = run.job_id
        if self.ledger is not None:
            # WAL the admission + restore what a predecessor incarnation
            # already finished of this job (matched by job_name — the wire
            # routes results by it and active names are unique), then
            # journal new transitions.
            from tpu_render_cluster.ha.failover import adopt_ledger

            # Settle queued appends first: the replay this admission reads
            # must include every transition already scheduled (a closed
            # same-name generation still in the appender queue would
            # otherwise be re-admitted as open).
            if self.ledger_appender is not None:
                await self.ledger_appender.drain()
            _replayed, needs_stitch = adopt_ledger(
                run.state,
                self.ledger,
                metrics=self.metrics,
                spec=run.spec.job.to_dict(),
                job_id=run.job_id,
                weight=run.spec.weight,
                priority=run.spec.priority,
                appender=self.ledger_appender,
            )
            for frame_index in needs_stitch:
                self.assembly.schedule(run.state, frame_index)
        run.status = JOB_RUNNING
        run.admitted_at = now
        self._running.append(run.job_id)
        self._active_by_name[run.job_name] = run
        # SLO tracking from admission (the job's clock starts when it can
        # actually run, not while parked in the admission queue).
        self.slo.register_job(run.spec.job, started_at=now)
        self.metrics.counter(
            "sched_jobs_running_total", "Jobs admitted to the running set"
        ).inc()
        self.metrics.histogram(
            "sched_admission_wait_seconds",
            "Submit-to-admission wait per job",
        ).observe(max(0.0, now - run.submitted_at))
        self.span_tracer.instant(
            "job admitted",
            cat="sched",
            track=f"job {run.job_id}",
            args={"job_id": run.job_id, "job_name": run.job_name,
                  "wait_s": round(now - run.submitted_at, 6)},
        )
        logger.info("Job %s admitted (%r).", run.job_id, run.job_name)
        for worker in self.live_workers():
            try:
                await worker.send_job_started(
                    trace_id=run.state.trace_id, job_id=run.job_id
                )
            except Exception as e:  # noqa: BLE001 - heartbeat will evict it
                logger.warning(
                    "job-started announce to %08x failed: %s", worker.worker_id, e
                )

    # -- completion / cancellation -------------------------------------------

    def _finish_run(self, run: JobRun, status: str, now: float) -> None:
        run.status = status
        run.finished_at = now
        if self.ledger_appender is not None and run.state is not None:
            # Close the job's ledger lifecycle so a restarted service does
            # not re-admit it (and a later same-name submission starts a
            # fresh generation). Never-admitted cancels (state None) were
            # never journaled, so there is nothing to close. Scheduled
            # through the FIFO appender: ordered after the job's queued
            # unit appends, fsync'd off the scheduler loop.
            if status == JOB_FINISHED:
                self.ledger_appender.schedule(
                    self.ledger.append_job_finished, run.job_name
                )
            else:
                self.ledger_appender.schedule(
                    self.ledger.append_job_cancelled, run.job_name
                )
        # Final SLO verdict (deadline judged at the true end; no-op for
        # jobs without objectives or never admitted).
        self.slo.finish_job(run.job_name)
        counter = (
            "sched_jobs_finished_total"
            if status == JOB_FINISHED
            else "sched_jobs_cancelled_total"
        )
        help_text = (
            "Jobs that completed every frame"
            if status == JOB_FINISHED
            else "Jobs cancelled before completion"
        )
        self.metrics.counter(counter, help_text).inc()
        self.metrics.gauge(
            "sched_job_share",
            "Instantaneous in-flight share per job",
            labels=("job",),
        ).set(0.0, job=run.job_id)
        if run.admitted_at is not None:
            self.span_tracer.complete(
                "job",
                cat="sched",
                start_wall=run.admitted_at,
                duration=max(0.0, now - run.admitted_at),
                track=f"job {run.job_id}",
                args={
                    "job_id": run.job_id,
                    "job_name": run.job_name,
                    "status": status,
                    "weight": run.spec.weight,
                    "priority": run.spec.priority,
                    "preemptions": run.preemptions,
                },
            )
        else:
            self.span_tracer.instant(
                "job cancelled before admission",
                cat="sched",
                track=f"job {run.job_id}",
                args={"job_id": run.job_id, "job_name": run.job_name},
            )
        logger.info("Job %s %s (%r).", run.job_id, status, run.job_name)

    def _finalize_finished_jobs(self, now: float) -> None:
        for job_id in list(self._running):
            run = self._runs[job_id]
            if (
                run.state is not None
                and run.state.all_frames_finished()
                and (
                    self.assembly.has_pending(run.job_name)
                    or run.state.speculations
                )
            ):
                # A tiled job's last stitches are still writing — or a
                # speculation race is unresolved (the winner just landed;
                # the next speculation tick must unqueue the loser and
                # account the outcome): stay RUNNING (and keep the name
                # reserved) until both settle — a status poll must never
                # say "finished" before the frame files exist, and a
                # same-name resubmit must not race the old stitcher on
                # the same output path. The next tick finalizes.
                continue
            if run.state is not None and run.state.all_frames_finished():
                # Ghost copies of units an accepted late result finished:
                # nothing will render them now that the job is done, so
                # sweep their mirror entries (and close their flows)
                # before the job's name is released.
                state = run.state
                job_name = run.job_name
                for worker in self.live_workers():
                    worker.sweep_finished_units(
                        lambda name: state if name == job_name else None
                    )
                self._running.remove(job_id)
                self._wfq.remove(job_id)
                self._active_by_name.pop(run.job_name, None)
                self._finish_run(run, JOB_FINISHED, now)

    # -- fair-share dispatch --------------------------------------------------

    def _total_slots(self) -> int:
        return self.config.target_queue_size * len(self.live_workers())

    def _in_flight_cost(self, run: JobRun) -> float | None:
        """The job's in-flight work in predicted seconds, or None before
        the cost model has any worker history (all jobs fall back to unit
        counts together — the inputs stay commensurable)."""
        if not self.cost_service.model.has_history():
            return None
        assert run.state is not None
        total = 0.0
        for unit, record in run.state.frames.items():
            if record.status not in (
                FrameStatus.QUEUED_ON_WORKER,
                FrameStatus.RENDERING_ON_WORKER,
            ) or record.worker_id is None:
                continue
            total += self.cost_service.predict_unit_seconds(
                record.worker_id, unit, run.spec.job
            )
        return total

    def _share_inputs(
        self, include_cost: bool | None = None
    ) -> list[fair_share.JobShareInput]:
        """Full rescan of every running job's share inputs (the legacy
        ``scan`` tick path, and the oracle ``verify`` mode checks the
        heap against). ``include_cost=False`` pins load metering to unit
        counts — verify mode does this on BOTH sides, because heap-vs-
        scan equivalence is exact there while cost predictions refresh
        on different schedules (per tick vs per dirty job)."""
        if include_cost is None:
            include_cost = self.config.tick_mode != "verify"
        out = []
        for job_id in self._running:
            run = self._runs[job_id]
            assert run.state is not None
            out.append(
                fair_share.JobShareInput(
                    job_id=job_id,
                    weight=run.spec.weight,
                    priority=run.spec.priority,
                    in_flight=run.state.in_flight_count(),
                    pending=run.state.pending_count(),
                    in_flight_cost=(
                        self._in_flight_cost(run) if include_cost else None
                    ),
                )
            )
        return out

    # -- incremental WFQ (heap/verify tick modes) -----------------------------

    def _cost_metered(self) -> bool:
        return (
            self.config.tick_mode != "verify"
            and self.cost_service.model.has_history()
        )

    def _sync_wfq(self) -> None:
        """Resync the WFQ entries of jobs whose state CHANGED since their
        last sync (the dirty set — state.version covers every transition,
        including evictions and steals that only move a unit between
        workers), drop departed jobs, and admit new ones. Pricing a dirty
        job walks its in-flight units (bounded by the pool's slots), not
        its whole frame table."""
        running = set(self._running)
        for job_id in self._wfq.job_ids():
            if job_id not in running:
                self._wfq.remove(job_id)
        cost_on = self._cost_metered()
        for job_id in self._running:
            run = self._runs[job_id]
            state = run.state
            assert state is not None
            if not self._wfq.needs_sync(job_id, state.version, cost_on):
                continue
            cost = None
            if cost_on:
                cost = 0.0
                for unit, worker_id in state.in_flight_units().items():
                    cost += self.cost_service.predict_unit_seconds(
                        worker_id, unit, run.spec.job
                    )
            self._wfq.sync(
                job_id,
                weight=run.spec.weight,
                priority=run.spec.priority,
                in_flight=state.in_flight_count(),
                pending=state.pending_count(),
                cost=cost,
                state_version=state.version,
            )

    def _tick_inputs(self) -> list[fair_share.JobShareInput]:
        """This tick's share inputs: a full rescan in ``scan`` mode, a
        dirty-jobs-only resync + O(jobs) entry read otherwise."""
        if self.config.tick_mode == "scan":
            return self._share_inputs()
        self._sync_wfq()
        return self._wfq.inputs()

    def _verify_pick(
        self,
        heap_pick: str | None,
        scan_inputs: list[fair_share.JobShareInput],
    ) -> None:
        """``verify`` tick mode: assert the heap's dispatch pick matches
        the legacy scan's over the same mid-tick information (the local
        dispatch counters — both sides see dispatches they made, neither
        sees events that landed during awaits). Picks inside the scan's
        ``_EPS`` tie tolerance (same priority, keys within 1e-9) may
        legitimately resolve either way; anything wider is a sync bug."""
        scan_pick = fair_share.pick_job_to_dispatch(scan_inputs)
        if heap_pick == scan_pick:
            return
        by_id = {job.job_id: job for job in scan_inputs}
        a = by_id.get(heap_pick) if heap_pick is not None else None
        b = by_id.get(scan_pick) if scan_pick is not None else None
        if (
            a is not None
            and b is not None
            and a.priority == b.priority
            and abs(a.load / a.weight - b.load / b.weight) <= 1e-9
        ):
            return
        raise AssertionError(
            f"WFQ heap/scan dispatch pick divergence: heap={heap_pick!r} "
            f"(key={self._wfq.key_of(heap_pick) if heap_pick else None}) "
            f"scan={scan_pick!r} over {scan_inputs!r}"
        )

    def _verify_preemption(
        self, wfq_inputs: list[fair_share.JobShareInput]
    ) -> None:
        """``verify`` tick mode: the preemption decision derived from the
        synced entries must equal the one from a fresh state rescan (both
        count-metered, so equality is exact)."""
        scan_inputs = self._share_inputs(include_cost=False)
        total = self._total_slots()
        scan_decision = fair_share.pick_preemption(
            scan_inputs, fair_share.compute_slot_targets(scan_inputs, total)
        )
        wfq_decision = fair_share.pick_preemption(
            wfq_inputs, fair_share.compute_slot_targets(wfq_inputs, total)
        )
        if scan_decision != wfq_decision:
            raise AssertionError(
                f"WFQ heap/scan preemption divergence: heap={wfq_decision!r} "
                f"scan={scan_decision!r} over {scan_inputs!r}"
            )

    def _compute_targets(
        self, inputs: list[fair_share.JobShareInput] | None = None
    ) -> dict[str, float]:
        # ``inputs`` lets the tick loop compute _share_inputs (an
        # O(frames)-per-job scan for the predicted in-flight cost) ONCE
        # and reuse it across targets/accounting/dispatch.
        if inputs is None:
            inputs = self._share_inputs()
        return fair_share.compute_slot_targets(inputs, self._total_slots())

    def _account_shares(
        self,
        dt: float,
        targets: dict[str, float],
        inputs: list[fair_share.JobShareInput] | None = None,
    ) -> None:
        """Fold one tick into the share gauges + overlap-window integrals."""
        if dt <= 0.0:
            return
        if inputs is None:
            inputs = self._share_inputs()
        total_slots = self._total_slots()
        total_in_flight = sum(job.in_flight for job in inputs)
        overlapping = len(inputs) >= 2
        share_gauge = self.metrics.gauge(
            "sched_job_share",
            "Instantaneous in-flight share per job",
            labels=("job",),
        )
        target_gauge = self.metrics.gauge(
            "sched_job_target_share",
            "Fair-share target share per job",
            labels=("job",),
        )
        for job in inputs:
            run = self._runs[job.job_id]
            target_share = (
                targets.get(job.job_id, 0.0) / total_slots if total_slots else 0.0
            )
            achieved_share = (
                job.in_flight / total_in_flight if total_in_flight else 0.0
            )
            run.last_target_share = target_share
            share_gauge.set(achieved_share, job=job.job_id)
            target_gauge.set(target_share, job=job.job_id)
            if overlapping:
                run.overlap_in_flight_integral += job.in_flight * dt
                run.overlap_total_integral += total_in_flight * dt
                run.overlap_target_integral += target_share * dt
                run.overlap_seconds += dt

    async def _dispatch_tick(
        self, inputs: list[fair_share.JobShareInput] | None = None
    ) -> None:
        """Fill every under-target worker with the fairest job's frames.

        ``heap`` mode picks each slot's job with an O(log n) heap peek
        and folds the dispatch into the entry; ``scan`` keeps the legacy
        per-slot O(jobs) input rebuild over local counters; ``verify``
        runs both and asserts every pick agrees (dispatch decisions
        follow the scan so a tolerated near-tie divergence cannot
        compound).
        """
        mode = self.config.tick_mode
        use_heap = mode in ("heap", "verify")
        track_counts = mode in ("scan", "verify")
        # Local counters adjusted as dispatches land, so one tick's fills
        # interleave jobs fairly instead of recounting O(frames) per slot.
        # The third element is the job's predicted in-flight seconds
        # (None before cost-model history): the WFQ pick meters load by
        # it, and each dispatch folds its unit's prediction in so one
        # tick's fills stay cost-fair too.
        counts: dict[str, list] = {}
        if track_counts:
            for job in inputs if inputs is not None else self._share_inputs():
                counts[job.job_id] = [
                    job.in_flight, job.pending, job.in_flight_cost
                ]

        def inputs_now() -> list[fair_share.JobShareInput]:
            out = []
            for job_id in self._running:
                if job_id not in counts:
                    continue
                run = self._runs[job_id]
                in_flight, pending, in_flight_cost = counts[job_id]
                out.append(
                    fair_share.JobShareInput(
                        job_id=job_id,
                        weight=run.spec.weight,
                        priority=run.spec.priority,
                        in_flight=in_flight,
                        pending=pending,
                        in_flight_cost=in_flight_cost,
                    )
                )
            return out

        workers = sorted(self.live_workers(), key=lambda w: len(w.queue))
        for worker in workers:
            while (
                not worker.is_dead
                and len(worker.queue) < self.config.target_queue_size
            ):
                if mode == "heap":
                    job_id = self._wfq.pick_dispatch()
                else:
                    if mode == "verify":
                        self._verify_pick(self._wfq.pick_dispatch(), inputs_now())
                    job_id = fair_share.pick_job_to_dispatch(inputs_now())
                if job_id is None:
                    return  # nothing pending anywhere
                run = self._runs[job_id]
                assert run.state is not None
                # Price the unit dispatch_one_pending is about to claim
                # (the pool head) BEFORE the await so the local cost
                # ledger can fold it in when the RPC lands.
                next_unit = run.state.next_pending_unit()
                predicted = (
                    self.cost_service.predict_unit_seconds(
                        worker.worker_id, next_unit, run.spec.job
                    )
                    if next_unit is not None
                    else 0.0
                )
                if await dispatch_one_pending(
                    worker, run.spec.job, run.state, job_id=job_id
                ):
                    if track_counts:
                        counts[job_id][0] += 1
                        counts[job_id][1] -= 1
                        if counts[job_id][2] is not None:
                            counts[job_id][2] += predicted
                    if use_heap:
                        self._wfq.on_dispatched(job_id, predicted)
                else:
                    # Dispatch failed (worker died mid-RPC, cancel raced,
                    # or the pending pool emptied under us): stop filling
                    # this worker; the pending count is refreshed next tick.
                    if track_counts:
                        counts[job_id][1] = max(0, counts[job_id][1] - 1)
                    if use_heap:
                        self._wfq.on_dispatch_failed(job_id)
                    break

    async def _preempt_tick(self) -> None:
        # 0 legitimately disables per-tick preemption without touching
        # TRC_SCHED_PREEMPTION.
        for _ in range(max(0, self.config.max_preemptions_per_tick)):
            # Recomputed per iteration on purpose (dispatch and any prior
            # preemption changed the in-flight picture) — but ONCE per
            # iteration, shared by targets and the preemption pick. The
            # heap path's recompute is a dirty-jobs resync + O(jobs)
            # entry read (the transitions dispatch just made making those
            # jobs dirty), no frame scans.
            inputs = self._tick_inputs()
            if self.config.tick_mode == "verify":
                self._verify_preemption(inputs)
            targets = self._compute_targets(inputs)
            decision = fair_share.pick_preemption(inputs, targets)
            if decision is None:
                return
            over_id, starved_id = decision
            run = self._runs[over_id]
            assert run.state is not None
            found = self._find_preemptible_frame(run.job_name)
            if found is None:
                return  # everything the job holds is already rendering
            victim, frame = found
            if not await preempt_frame(
                run.spec.job, run.state, victim, frame.unit
            ):
                return
            run.preemptions += 1
            self.metrics.counter(
                "sched_preemptions_total",
                "Frames unqueued from over-share jobs back to their pool",
                labels=("job",),
            ).inc(job=over_id)
            self.span_tracer.instant(
                "preempt",
                cat="sched",
                track=f"job {over_id}",
                args={
                    "job_id": over_id,
                    "for_job": starved_id,
                    "frame": frame.frame_index,
                    "worker": f"{victim.worker_id:08x}",
                },
            )

    def _find_preemptible_frame(
        self, job_name: str
    ) -> tuple[WorkerHandle, Any] | None:
        """The job's NEWEST not-yet-rendering mirrored frame (preempting
        the most recently queued wastes the least accumulated wait and is
        the frame least likely to be picked up mid-RPC)."""
        best: tuple[WorkerHandle, Any] | None = None
        for worker in self.live_workers():
            for frame in worker.queue.frames_for_job(job_name):
                if frame.is_rendering:
                    continue
                if best is None or frame.queued_at > best[1].queued_at:
                    best = (worker, frame)
        return best
