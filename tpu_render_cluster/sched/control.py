"""JSON-lines control plane for the multi-job scheduler.

One request per line, one response per line, over a plain TCP socket —
deliberately NOT the worker WebSocket protocol, so the reference-shaped
worker wire surface stays untouched and a shell script can drive the
scheduler with ``nc``. Operations:

- ``{"op": "submit", "spec": {"job": {...BlenderJob...}, "weight": 3, "priority": 0}}``
  -> ``{"ok": true, "job_id": "job-0001"}``
- ``{"op": "status"}`` -> ``{"ok": true, "sched": {...scheduler_view...}}``
- ``{"op": "status", "job_id": "job-0001"}`` -> ``{"ok": true, "job": {...}}``
- ``{"op": "cancel", "job_id": "job-0001"}`` -> ``{"ok": true, "cancelled": bool}``
- ``{"op": "drain"}`` -> stop admitting; the service exits when idle
- ``{"op": "migrate_workers", "count": 2, "host": "...", "port": N}``
  -> ``{"ok": true, "migrating": n}`` — shed up to ``count`` workers
  toward another shard master (the router's rebalance move)
- ``{"op": "alerts"}`` -> ``{"ok": true, "alerts": [...], "slo": {...}}``
  — the SLO engine's structured alert log (obs/slo.py: one ``fire`` per
  breach episode, one ``clear`` per recovery) plus the live per-job
  attainment/burn view
- ``{"op": "ping"}`` -> liveness

Errors come back as ``{"ok": false, "error": "..."}``; the connection
survives them (a client can retry a fixed submission on the same socket).
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import TYPE_CHECKING, Any

from tpu_render_cluster.sched.models import JobSpec

if TYPE_CHECKING:
    from tpu_render_cluster.sched.manager import JobManager

logger = logging.getLogger(__name__)

MAX_LINE_BYTES = 16 * 1024 * 1024  # a job TOML payload is tiny; be generous


async def handle_request(manager: "JobManager", request: dict[str, Any]) -> dict[str, Any]:
    """Execute one control operation against the manager (pure dispatch —
    shared by the TCP server and in-process callers/tests)."""
    op = request.get("op")
    try:
        if op == "ping":
            return {"ok": True, "pong": True}
        if op == "submit":
            spec = JobSpec.from_dict(request.get("spec") or {})
            job_id = manager.submit(spec)
            return {"ok": True, "job_id": job_id}
        if op == "status":
            job_id = request.get("job_id")
            if job_id is None:
                return {"ok": True, "sched": manager.scheduler_view()}
            view = manager.job_status(str(job_id))
            if view is None:
                return {"ok": False, "error": f"unknown job_id: {job_id!r}"}
            return {"ok": True, "job": view}
        if op == "cancel":
            job_id = request.get("job_id")
            if job_id is None:
                return {"ok": False, "error": "cancel requires job_id"}
            cancelled = await manager.cancel_job(str(job_id))
            return {"ok": True, "cancelled": cancelled}
        if op == "drain":
            manager.request_drain()
            return {"ok": True, "draining": True}
        if op == "migrate_workers":
            host = request.get("host")
            port = request.get("port")
            if not host or port is None:
                return {"ok": False, "error": "migrate_workers requires host and port"}
            moved = await manager.migrate_workers(
                int(request.get("count", 1)),
                str(host),
                int(port),
                reason=request.get("reason"),
            )
            return {"ok": True, "migrating": moved}
        if op == "alerts":
            return {
                "ok": True,
                "alerts": manager.slo.alerts_view(),
                "slo": manager.slo.view(),
            }
        return {"ok": False, "error": f"unknown op: {op!r}"}
    except (ValueError, RuntimeError, KeyError, TypeError) as e:
        return {"ok": False, "error": str(e)}


class ControlServer:
    """The TCP JSON-lines frontend over ``handle_request``."""

    def __init__(
        self, manager: "JobManager", host: str = "127.0.0.1", port: int = 0
    ) -> None:
        self.manager = manager
        self.host = host
        self.port = port
        self._server: asyncio.Server | None = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._on_connection, self.host, self.port, limit=MAX_LINE_BYTES
        )
        self.port = self._server.sockets[0].getsockname()[1]
        logger.info("Scheduler control listening on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), 5.0)
            except asyncio.TimeoutError:
                logger.warning("Control server close timed out.")

    async def _on_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        peer = writer.get_extra_info("peername")
        try:
            while True:
                line = await reader.readline()
                if not line:
                    return
                try:
                    request = json.loads(line)
                    if not isinstance(request, dict):
                        raise ValueError("request must be a JSON object")
                except (json.JSONDecodeError, ValueError) as e:
                    response: dict[str, Any] = {"ok": False, "error": f"bad request: {e}"}
                else:
                    response = await handle_request(self.manager, request)
                writer.write(json.dumps(response).encode("utf-8") + b"\n")
                await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError):
            pass  # client went away mid-request; nothing to answer
        except Exception as e:  # noqa: BLE001 - one bad client must not kill the plane
            logger.warning("Control connection from %s failed: %s", peer, e)
        finally:
            writer.close()


async def control_request(
    host: str, port: int, request: dict[str, Any], *, timeout: float = 30.0
) -> dict[str, Any]:
    """One-shot client: connect, send one request line, read the answer."""
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(host, port, limit=MAX_LINE_BYTES), timeout
    )
    try:
        writer.write(json.dumps(request).encode("utf-8") + b"\n")
        await writer.drain()
        line = await asyncio.wait_for(reader.readline(), timeout)
        if not line:
            raise ConnectionError("control server closed the connection")
        response = json.loads(line)
        if not isinstance(response, dict):
            raise ValueError("control response must be a JSON object")
        return response
    finally:
        writer.close()


def control_request_sync(
    host: str, port: int, request: dict[str, Any], *, timeout: float = 30.0
) -> dict[str, Any]:
    return asyncio.run(control_request(host, port, request, timeout=timeout))
