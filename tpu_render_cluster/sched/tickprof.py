"""Scheduler tick phase profiler.

ROADMAP item 3 asserts the sched tick's share scan and per-dispatch JSON
payloads dominate the control-plane profile; this module produces the
committed profile that claim (and any incremental-WFQ rewrite beating
it) is measured against. ``JobManager._scheduler_loop`` brackets each
tick with ``begin_tick``/``end_tick`` and wraps its phases — cost-model
``pricing``, ``share_scan``, ``fair_share`` pick, ``dispatch``,
``preempt``, ``speculation`` — in ``phase()`` contexts. Each phase and
the whole tick feed the ``sched_tick_seconds{phase}`` histogram
(``phase="total"`` for the tick) and draw spans on a dedicated "sched"
Perfetto track; ``sched_tick_budget_ratio`` is a rolling gauge of mean
tick time over the configured tick budget (``> 1`` means the loop can
no longer hold its cadence).

The dispatch RPC round-trip and the queue-add JSON serialize happen off
the tick's critical section (inside ``WorkerHandle``), so those sites
report through :func:`observe_dispatch_phase` instead — same histogram,
phases ``dispatch_rpc_await`` / ``dispatch_serialize`` — keeping the
metric name owned here.

``TRC_SCHED_PROFILE=0`` disables recording (consulted per tick, so
tests and long-lived processes can flip it live).
"""

from __future__ import annotations

import time
from collections import deque
from contextlib import contextmanager

from tpu_render_cluster.utils.env import env_str

__all__ = [
    "TickProfiler",
    "observe_dispatch_phase",
    "LOOP_PHASES",
    "TICK_METRIC",
    "BUDGET_METRIC",
]

TICK_METRIC = "sched_tick_seconds"
BUDGET_METRIC = "sched_tick_budget_ratio"

_TICK_HELP = "Scheduler tick time by phase (phase=total covers the whole tick)"
_BUDGET_HELP = "Rolling mean tick time over the tick budget (>1 = overrun)"

# Phases recorded INSIDE one tick's begin/end bracket; their per-tick sum
# is bounded by the tick's phase="total" wall time (the phase-sum test).
LOOP_PHASES = (
    "pricing",
    "share_scan",
    "fair_share",
    "dispatch",
    "preempt",
    "speculation",
)

# Ticks folded into the rolling budget gauge.
BUDGET_WINDOW = 32


def profiling_enabled() -> bool:
    return (env_str("TRC_SCHED_PROFILE", "1") or "").strip() not in ("0", "off")


class TickProfiler:
    """Per-tick phase timing for one scheduler loop."""

    def __init__(
        self,
        metrics,
        span_tracer=None,
        *,
        tick_budget_seconds: float = 0.05,
        flightrec=None,
    ) -> None:
        self.metrics = metrics
        self.span_tracer = span_tracer
        self.tick_budget_seconds = max(1e-9, tick_budget_seconds)
        self.ticks = 0
        self._hist = metrics.histogram(TICK_METRIC, _TICK_HELP, labels=("phase",))
        self._budget = metrics.gauge(BUDGET_METRIC, _BUDGET_HELP)
        self._totals: deque[float] = deque(maxlen=BUDGET_WINDOW)
        self._tick_active = False
        self._tick_start_wall = 0.0
        self._tick_start = 0.0
        # Flight-recorder seam (obs/flightrec.py): a rolling budget ratio
        # crossing 1.0 dumps a black box, like loop_lag does. Edge-
        # triggered here (fire on the below->above crossing, re-arm on
        # dropping back under) on top of the recorder's own per-kind
        # debounce, so a sustained overrun is one dump, not one per tick.
        self.flightrec = flightrec
        self._over_budget = False

    def begin_tick(self) -> None:
        self._tick_active = profiling_enabled()
        if not self._tick_active:
            return
        self._tick_start_wall = time.time()
        self._tick_start = time.perf_counter()

    @contextmanager
    def phase(self, name: str):
        if not self._tick_active:
            yield
            return
        start_wall = time.time()
        started = time.perf_counter()
        try:
            yield
        finally:
            elapsed = time.perf_counter() - started
            self._hist.observe(elapsed, phase=name)
            if self.span_tracer is not None:
                self.span_tracer.complete(
                    name,
                    cat="sched",
                    start_wall=start_wall,
                    duration=elapsed,
                    track="sched",
                )

    def end_tick(self) -> None:
        if not self._tick_active:
            return
        self._tick_active = False
        total = time.perf_counter() - self._tick_start
        self.ticks += 1
        self._hist.observe(total, phase="total")
        self._totals.append(total)
        ratio = sum(self._totals) / len(self._totals) / self.tick_budget_seconds
        self._budget.set(ratio)
        if self.flightrec is not None:
            if ratio > 1.0:
                if not self._over_budget:
                    self._over_budget = True
                    from tpu_render_cluster.obs.flightrec import (
                        TRIGGER_TICK_BUDGET,
                    )

                    self.flightrec.trigger(
                        TRIGGER_TICK_BUDGET,
                        {
                            "budget_ratio": round(ratio, 4),
                            "tick_budget_seconds": self.tick_budget_seconds,
                            "last_tick_seconds": round(total, 6),
                            "ticks": self.ticks,
                        },
                    )
            else:
                self._over_budget = False
        if self.span_tracer is not None:
            self.span_tracer.complete(
                "sched tick",
                cat="sched",
                start_wall=self._tick_start_wall,
                duration=total,
                track="sched",
                args={"tick": self.ticks},
            )


def observe_dispatch_phase(metrics, phase: str, seconds: float) -> None:
    """Record an off-tick dispatch cost into ``sched_tick_seconds``.

    Used by the master's per-worker handles for ``dispatch_rpc_await``
    (queue-add send -> ack) and ``dispatch_serialize`` (queue-add JSON
    encode); no-op when profiling is off or no registry is wired.
    """
    if metrics is None or not profiling_enabled():
        return
    metrics.histogram(TICK_METRIC, _TICK_HELP, labels=("phase",)).observe(
        seconds, phase=phase
    )
