"""Live shard rebalancing: move worker slices from hot shards to cold.

The router (ha/shards.py) partitions the job keyspace by crc32, which
balances *submissions* but not *work*: one shard can end up with the
render-heavy jobs while another idles. This module closes that loop.
Each shard already exposes its own load summary through the control
plane (``scheduler_view()["rebalance"]``: backlog units, the PR-8 cost
model's predicted in-flight seconds, live workers); the router collects
those, and when one shard's per-worker load stays persistently above
another's, it tells the hot shard to shed workers toward the cold one
(the ``migrate_workers`` control op -> per-worker migrate goodbye ->
fresh announce on the target shard).

Split in the proven chaos-planner style: a PURE planner
(``RebalancePlanner.observe``) that turns load snapshots into at most
one ``Move`` per tick — deterministic, clock-injected, unit-testable
without sockets — and a thin async ``RebalanceLoop`` that feeds it real
scrapes and executes its moves.

Stability over speed: migration is expensive (a drain + reconnect per
worker), so the planner is deliberately sluggish —

- **threshold**: the hot shard's per-worker load must exceed the cold
  shard's by a multiplicative factor (``TRC_REBALANCE_THRESHOLD``), not
  merely be larger;
- **hysteresis**: the imbalance must persist for N consecutive ticks
  (``TRC_REBALANCE_HYSTERESIS_TICKS``) before the first move — a one-
  tick spike (a job finishing, a scrape racing a dispatch burst) never
  moves anyone;
- **cooldown**: after a move, no further moves for
  ``TRC_REBALANCE_COOLDOWN_SECONDS`` — migrated workers need time to
  drain, reconnect, and show up in the target's load before the next
  decision, otherwise the planner chases its own tail (flapping);
- **bounded moves**: at most ``TRC_REBALANCE_MAX_MOVES`` workers per
  move, and never below one worker left on the source shard.

Enable on the router with ``--rebalance`` (or ``TRC_REBALANCE=1``);
``TRC_REBALANCE_INTERVAL_SECONDS`` sets the scrape/decide cadence.
"""

from __future__ import annotations

import asyncio
import logging
import math
import time
from dataclasses import dataclass
from typing import TYPE_CHECKING, Any, Awaitable, Callable

from tpu_render_cluster.utils.env import env_float, env_int

if TYPE_CHECKING:
    from tpu_render_cluster.obs.registry import MetricsRegistry

logger = logging.getLogger(__name__)

__all__ = ["ShardLoad", "Move", "RebalancePlanner", "RebalanceLoop"]


def rebalance_enabled() -> bool:
    return env_int("TRC_REBALANCE", 0) != 0


def rebalance_interval_seconds() -> float:
    return max(0.05, env_float("TRC_REBALANCE_INTERVAL_SECONDS", 5.0))


@dataclass(frozen=True)
class ShardLoad:
    """One shard's load snapshot, as scraped from its control plane."""

    shard: int
    queue_depth: int
    in_flight_cost_seconds: float | None
    workers: int
    alive: bool = True

    @classmethod
    def from_view(cls, shard: int, view: dict[str, Any]) -> "ShardLoad":
        return cls(
            shard=shard,
            queue_depth=int(view.get("queue_depth", 0)),
            in_flight_cost_seconds=view.get("in_flight_cost_seconds"),
            workers=int(view.get("workers", 0)),
        )

    @classmethod
    def dead(cls, shard: int) -> "ShardLoad":
        return cls(
            shard=shard,
            queue_depth=0,
            in_flight_cost_seconds=None,
            workers=0,
            alive=False,
        )


@dataclass(frozen=True)
class Move:
    """One planner decision: shed ``count`` workers source -> target."""

    source: int
    target: int
    count: int
    reason: str


class RebalancePlanner:
    """Pure hot->cold move planner with threshold/hysteresis/cooldown.

    ``observe(loads, now)`` is the whole API: feed it one snapshot per
    tick and it returns at most one ``Move`` (or None). All state is a
    consecutive-imbalance streak and the last-move timestamp; the clock
    is an argument, so tests drive it deterministically.
    """

    def __init__(
        self,
        *,
        threshold: float | None = None,
        hysteresis_ticks: int | None = None,
        cooldown_seconds: float | None = None,
        max_moves: int | None = None,
    ) -> None:
        self.threshold = (
            threshold
            if threshold is not None
            else max(1.0, env_float("TRC_REBALANCE_THRESHOLD", 2.0))
        )
        self.hysteresis_ticks = (
            hysteresis_ticks
            if hysteresis_ticks is not None
            else max(1, env_int("TRC_REBALANCE_HYSTERESIS_TICKS", 3))
        )
        self.cooldown_seconds = (
            cooldown_seconds
            if cooldown_seconds is not None
            else max(0.0, env_float("TRC_REBALANCE_COOLDOWN_SECONDS", 30.0))
        )
        self.max_moves = (
            max_moves
            if max_moves is not None
            else max(1, env_int("TRC_REBALANCE_MAX_MOVES", 2))
        )
        self._streak = 0
        self._last_move_at = -math.inf

    @staticmethod
    def _per_worker_load(load: ShardLoad, use_cost: bool) -> float:
        raw = (
            float(load.in_flight_cost_seconds or 0.0)
            if use_cost
            else float(load.queue_depth)
        )
        return raw / max(1, load.workers)

    def observe(self, loads: list[ShardLoad], now: float) -> Move | None:
        """One decision tick. Dead shards are excluded — their workers
        re-home through the router's routing path, not through migrate
        ops a dead control plane cannot serve. Cost-based load is only
        used when EVERY live shard reports it (commensurable inputs,
        same rule as the scheduler's own fair-share fallback)."""
        live = [load for load in loads if load.alive]
        if len(live) < 2:
            self._streak = 0
            return None
        use_cost = all(
            load.in_flight_cost_seconds is not None for load in live
        )
        hot = max(live, key=lambda load: self._per_worker_load(load, use_cost))
        cold = min(live, key=lambda load: self._per_worker_load(load, use_cost))
        hot_load = self._per_worker_load(hot, use_cost)
        cold_load = self._per_worker_load(cold, use_cost)
        imbalanced = (
            hot.shard != cold.shard
            and hot.workers >= 2
            and hot_load > 0.0
            and hot_load > cold_load * self.threshold
        )
        if not imbalanced:
            self._streak = 0
            return None
        self._streak += 1
        if self._streak < self.hysteresis_ticks:
            return None
        if now - self._last_move_at < self.cooldown_seconds:
            return None
        # Move toward even worker counts, never emptying the source and
        # never more than max_moves at once.
        count = min(
            self.max_moves,
            max(1, (hot.workers - cold.workers) // 2),
            hot.workers - 1,
        )
        self._streak = 0
        self._last_move_at = now
        return Move(
            source=hot.shard,
            target=cold.shard,
            count=count,
            reason=(
                f"per-worker load {hot_load:.3f} vs {cold_load:.3f} "
                f"({'cost' if use_cost else 'units'}) for "
                f"{self.hysteresis_ticks} ticks"
            ),
        )


class RebalanceLoop:
    """The router's async harness around the pure planner.

    Dependency-injected at the edges (``loads_fn`` scrapes, ``move_fn``
    executes) so it carries no socket code of its own and the router can
    reuse its existing degradation-aware fan-out for both.
    """

    def __init__(
        self,
        loads_fn: Callable[[], Awaitable[list[ShardLoad]]],
        move_fn: Callable[[Move], Awaitable[int]],
        *,
        planner: RebalancePlanner | None = None,
        interval_seconds: float | None = None,
        metrics: "MetricsRegistry | None" = None,
    ) -> None:
        self.loads_fn = loads_fn
        self.move_fn = move_fn
        self.planner = planner if planner is not None else RebalancePlanner()
        self.interval_seconds = (
            interval_seconds
            if interval_seconds is not None
            else rebalance_interval_seconds()
        )
        self.metrics = metrics
        self.moves: list[dict[str, Any]] = []
        self._running = False
        self._task: asyncio.Task | None = None

    def start(self) -> None:
        self._running = True
        self._task = asyncio.create_task(self.run(), name="rebalance-loop")

    async def stop(self) -> None:
        self._running = False
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._task = None

    async def run(self) -> None:
        self._running = True
        while self._running:
            await asyncio.sleep(self.interval_seconds)
            try:
                await self.tick()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 - keep deciding through chaos
                logger.warning("Rebalance tick failed: %s", e)

    async def tick(self) -> Move | None:
        """One scrape+decide+execute round (tests call this directly)."""
        loads = await self.loads_fn()
        if self.metrics is not None:
            gauge = self.metrics.gauge(
                "ha_router_shard_load_units",
                "Per-shard backlog (pending + in-flight units) as last "
                "scraped by the rebalancer",
                labels=("shard",),
            )
            for load in loads:
                gauge.set(float(load.queue_depth), shard=str(load.shard))
        move = self.planner.observe(loads, time.time())
        if move is None:
            return None
        moved = await self.move_fn(move)
        logger.info(
            "Rebalance: shard %d -> shard %d, %d/%d workers (%s).",
            move.source, move.target, moved, move.count, move.reason,
        )
        self.moves.append(
            {
                "at": time.time(),
                "source": move.source,
                "target": move.target,
                "requested": move.count,
                "moved": moved,
                "reason": move.reason,
            }
        )
        if self.metrics is not None:
            self.metrics.counter(
                "ha_router_rebalance_moves_total",
                "Worker migrations executed by the rebalancer, by edge",
                labels=("source", "target"),
            ).inc(moved, source=str(move.source), target=str(move.target))
        return move
