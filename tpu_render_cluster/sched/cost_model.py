"""The predictive-scheduling cost model: trace-trained, persistable, online.

ROADMAP item 3 (DOPPLER, PAPERS.md): the tpu-batch auction already prices
assignments from a per-worker speed EMA times a per-frame complexity
factor, but the model was cold-started every run, tile-blind, and private
to one strategy loop. This module makes it a first-class subsystem:

- ``JointCostModel`` — the multiplicative decomposition
  ``t(worker, unit) ~ speed[worker] * complexity[scene, frame] * pixels``,
  now with a SCENE dimension (per-(scene, worker) predictors — one worker
  speed table shared across scenes, one complexity curve per scene) and
  pixel-fraction normalization so a ``(frame, tile)`` unit is priced at
  its share of the frame, not the whole frame.
- **Offline training** — ``fit_cost_model`` fits the model from recorded
  per-unit render samples (``samples_from_cluster_trace`` extracts them
  from a merged cluster timeline; ``samples_from_statistics`` recovers
  coarse per-worker speed priors from a ``statistics.json``), smoothing
  the complexity curve with a pure-numpy ridge polynomial
  (``ComplexityCurve``) that also extrapolates to unseen frames.
- **Persistence** — ``to_dict``/``from_dict``/``save``/``load`` round-trip
  the whole model as JSON; ``load_cost_model_from_env`` loads it at master
  start from ``TRC_COST_MODEL``, and master/persist.py snapshots it next
  to the run's results so a resumed master starts warm.
- ``CostModelService`` — the shared ONLINE ingestion point: one instance
  per master drains every worker's completion observations exactly once,
  folds them into the model through the same EMA the auction always used,
  and accounts prediction quality (``sched_cost_model_abs_error_seconds``)
  for the ``prediction`` section of statistics.json.

The model classes started life in master/tpu_batch.py (which re-exports
them for compatibility); the strategy file keeps only the auction/tick
machinery.

CLI (offline training)::

    python -m tpu_render_cluster.sched.cost_model \
        results/cluster-runs/..._cluster_trace-events.json -o model.json
    TRC_COST_MODEL=model.json python -m tpu_render_cluster.master.main ...
"""

from __future__ import annotations

import bisect
import json
import logging
from collections import deque
from pathlib import Path
from typing import TYPE_CHECKING, Any, Callable, Iterable, NamedTuple, Sequence

import numpy as np

from tpu_render_cluster.jobs.tiles import WorkUnit, unit_pixel_fraction
from tpu_render_cluster.utils.env import env_str

if TYPE_CHECKING:
    from tpu_render_cluster.jobs.models import BlenderJob
    from tpu_render_cluster.master.worker_handle import WorkerHandle
    from tpu_render_cluster.obs import MetricsRegistry

logger = logging.getLogger(__name__)

DEFAULT_FRAME_TIME_GUESS = 5.0  # seconds, until history arrives
DEFAULT_COST_EMA_ALPHA = 0.3  # matches TpuBatchStrategyOptions.cost_ema_alpha
# Default scene key: single-scene masters and legacy callers that never
# name a scene all share one complexity curve.
DEFAULT_SCENE = ""

MODEL_FORMAT_VERSION = 1


class WorkerCostModel:
    """Per-worker EMA frame-time predictor fed by finished events."""

    def __init__(self, alpha: float) -> None:
        self.alpha = alpha
        self._ema: dict[int, float] = {}

    def observe(self, worker_id: int, frame_seconds: float) -> None:
        previous = self._ema.get(worker_id)
        if previous is None:
            self._ema[worker_id] = frame_seconds
        else:
            self._ema[worker_id] = (
                self.alpha * frame_seconds + (1 - self.alpha) * previous
            )

    def has_history(self, worker_id: int) -> bool:
        return worker_id in self._ema

    def any_history(self) -> bool:
        return bool(self._ema)

    def predict(self, worker_id: int) -> float:
        value = self._ema.get(worker_id)
        if value is not None:
            # Hot path (scheduler ticks predict known workers O(jobs x
            # in-flight) times per tick): no median over the whole table.
            return value
        if self._ema:
            return float(np.median(list(self._ema.values())))
        return DEFAULT_FRAME_TIME_GUESS

    def to_dict(self) -> dict[str, Any]:
        # Worker ids are ints; JSON keys must be strings.
        return {
            "alpha": self.alpha,
            "ema": {str(worker_id): v for worker_id, v in self._ema.items()},
        }

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "WorkerCostModel":
        model = cls(float(data.get("alpha", DEFAULT_COST_EMA_ALPHA)))
        for worker_id, value in (data.get("ema") or {}).items():
            model._ema[int(worker_id)] = float(value)
        return model


class ComplexityCurve:
    """Ridge-fitted polynomial complexity-over-frame-index prior.

    Pure numpy, closed-form ridge over a normalized frame axis; used by
    ``FrameComplexityModel`` to predict frames the online EMA has never
    seen (a trace-trained model knows the SHAPE of the scene's cost curve
    even for frame ranges a previous run never rendered). Clamped light
    extrapolation: a cubic fit must not explode outside the training
    range."""

    def __init__(
        self, coefficients: Sequence[float], frame_lo: int, frame_hi: int
    ) -> None:
        self.coefficients = np.asarray(coefficients, dtype=np.float64)
        self.frame_lo = int(frame_lo)
        self.frame_hi = int(frame_hi)

    def _features(self, frame_index: np.ndarray) -> np.ndarray:
        span = max(1, self.frame_hi - self.frame_lo)
        t = (frame_index - self.frame_lo) / span
        t = np.clip(t, -0.25, 1.25)
        return np.stack(
            [t**d for d in range(len(self.coefficients))], axis=-1
        )

    def predict(self, frame_index: int) -> float:
        value = float(
            self._features(np.asarray([frame_index], dtype=np.float64))[0]
            @ self.coefficients
        )
        return max(1e-6, value)

    @classmethod
    def fit(
        cls,
        frames: Sequence[int],
        values: Sequence[float],
        *,
        degree: int = 3,
        ridge_lambda: float = 1e-3,
    ) -> "ComplexityCurve":
        frames_arr = np.asarray(frames, dtype=np.float64)
        values_arr = np.asarray(values, dtype=np.float64)
        frame_lo, frame_hi = int(frames_arr.min()), int(frames_arr.max())
        # Never fit more coefficients than distinct support points.
        degree = max(0, min(degree, len(set(map(int, frames))) - 1))
        curve = cls(np.zeros(degree + 1), frame_lo, frame_hi)
        features = curve._features(frames_arr)
        gram = features.T @ features + ridge_lambda * np.eye(degree + 1)
        curve.coefficients = np.linalg.solve(gram, features.T @ values_arr)
        return curve

    def to_dict(self) -> dict[str, Any]:
        return {
            "coefficients": [float(c) for c in self.coefficients],
            "frame_lo": self.frame_lo,
            "frame_hi": self.frame_hi,
        }

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "ComplexityCurve":
        return cls(
            data["coefficients"], int(data["frame_lo"]), int(data["frame_hi"])
        )


class FrameComplexityModel:
    """Per-frame relative render-cost predictor.

    Scenes are animated, so cost varies smoothly with frame index; unseen
    frames are predicted by linear interpolation between the nearest
    observed frame indices (nearest-neighbor at the edges). Observations
    are worker-speed-normalized, so a heavy frame on a fast worker and a
    light frame on a slow worker are distinguishable. Cold start predicts
    the trace-trained ridge curve when one is attached, else a flat 1.0
    (which reduces the cost matrix to the pure worker-speed model).
    """

    def __init__(self, alpha: float = 0.5) -> None:
        self.alpha = alpha
        self._complexity: dict[int, float] = {}
        self._sorted_indices: list[int] = []
        # Offline-fit prior for frames outside the observed support
        # (fit_cost_model attaches it; online observations always win).
        self.curve: ComplexityCurve | None = None

    def observe(self, frame_index: int, relative_complexity: float) -> None:
        previous = self._complexity.get(frame_index)
        if previous is None:
            bisect.insort(self._sorted_indices, frame_index)
            self._complexity[frame_index] = relative_complexity
        else:
            self._complexity[frame_index] = (
                self.alpha * relative_complexity + (1 - self.alpha) * previous
            )

    def predict(self, frame_index: int) -> float:
        if not self._sorted_indices:
            if self.curve is not None:
                return self.curve.predict(frame_index)
            return 1.0
        known = self._complexity.get(frame_index)
        if known is not None:
            return known
        position = bisect.bisect_left(self._sorted_indices, frame_index)
        if position == 0 or position == len(self._sorted_indices):
            # Outside the observed support: the fitted curve (when
            # present) knows the scene's shape beyond the edge; the
            # nearest-neighbor edge value is the cold fallback.
            if self.curve is not None:
                return self.curve.predict(frame_index)
            edge = 0 if position == 0 else -1
            return self._complexity[self._sorted_indices[edge]]
        left = self._sorted_indices[position - 1]
        right = self._sorted_indices[position]
        weight = (frame_index - left) / (right - left)
        return (1 - weight) * self._complexity[left] + weight * self._complexity[right]

    def predict_many(self, frames: Sequence[int]) -> dict[int, float]:
        return {frame_index: self.predict(frame_index) for frame_index in frames}

    def mean_observed(self) -> float:
        """Mean complexity over observed frames (1.0 before any history).

        Used to estimate the pending pool's total work without predicting
        every pending frame each tick (pools can be 14400 frames)."""
        if not self._complexity:
            return 1.0
        return float(np.mean(list(self._complexity.values())))

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "alpha": self.alpha,
            "complexity": {str(f): v for f, v in self._complexity.items()},
        }
        if self.curve is not None:
            out["curve"] = self.curve.to_dict()
        return out

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "FrameComplexityModel":
        model = cls(float(data.get("alpha", 0.5)))
        for frame_index, value in (data.get("complexity") or {}).items():
            model.observe(int(frame_index), float(value))
        if data.get("curve"):
            model.curve = ComplexityCurve.from_dict(data["curve"])
        return model


class JointCostModel:
    """Multiplicative decomposition t ~ speed[worker] * complexity[scene, frame].

    ``speed`` is a per-worker EMA in seconds per complexity unit
    (WorkerCostModel), shared across scenes (hardware speed is a property
    of the worker); ``complexity`` is a per-scene ``FrameComplexityModel``
    (scene content is what varies over frames). Each observation updates
    both: the worker EMA is fed the complexity-normalized time, and the
    frame model the speed-normalized time. The alternation converges
    because both models start from flat priors (1.0 complexity, median
    speed). A ``(frame, tile)`` unit's time is normalized by its pixel
    fraction before entering the model, so tiled and whole-frame
    observations feed ONE frame-equivalent scale.
    """

    def __init__(self, alpha: float = DEFAULT_COST_EMA_ALPHA) -> None:
        self.alpha = alpha
        self.worker_speed = WorkerCostModel(alpha)
        self._scenes: dict[str, FrameComplexityModel] = {
            DEFAULT_SCENE: FrameComplexityModel(alpha)
        }
        self.samples_observed = 0

    @property
    def frame_complexity(self) -> FrameComplexityModel:
        """The default scene's complexity model (single-scene callers)."""
        return self._scenes[DEFAULT_SCENE]

    def complexity_model(self, scene: str = DEFAULT_SCENE) -> FrameComplexityModel:
        model = self._scenes.get(scene)
        if model is None:
            model = self._scenes[scene] = FrameComplexityModel(self.alpha)
        return model

    def scenes(self) -> list[str]:
        return list(self._scenes)

    def has_history(self) -> bool:
        return self.worker_speed.any_history()

    def observe(
        self,
        worker_id: int,
        frame_index: int,
        seconds: float,
        *,
        scene: str = DEFAULT_SCENE,
        pixel_fraction: float = 1.0,
    ) -> None:
        # Frame-equivalent time: a quarter-frame tile that took 1 s means
        # the whole frame costs ~4 s on this worker.
        seconds = seconds / max(1e-9, pixel_fraction)
        complexity = self.complexity_model(scene)
        complexity_estimate = max(1e-6, complexity.predict(frame_index))
        self.worker_speed.observe(worker_id, seconds / complexity_estimate)
        speed_estimate = max(1e-6, self.worker_speed.predict(worker_id))
        complexity.observe(frame_index, seconds / speed_estimate)
        self.samples_observed += 1

    def predict_unit_seconds(
        self,
        worker_id: int,
        frame_index: int,
        *,
        scene: str = DEFAULT_SCENE,
        pixel_fraction: float = 1.0,
    ) -> float:
        return (
            self.worker_speed.predict(worker_id)
            * max(1e-6, self.complexity_model(scene).predict(frame_index))
            * pixel_fraction
        )

    # -- persistence ---------------------------------------------------------

    def to_dict(self) -> dict[str, Any]:
        return {
            "format_version": MODEL_FORMAT_VERSION,
            "alpha": self.alpha,
            "samples_observed": self.samples_observed,
            "worker_speed": self.worker_speed.to_dict(),
            "scenes": {
                scene: model.to_dict() for scene, model in self._scenes.items()
            },
        }

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "JointCostModel":
        version = int(data.get("format_version", MODEL_FORMAT_VERSION))
        if version > MODEL_FORMAT_VERSION:
            raise ValueError(
                f"Cost model format {version} is newer than this build "
                f"understands ({MODEL_FORMAT_VERSION})."
            )
        model = cls(float(data.get("alpha", DEFAULT_COST_EMA_ALPHA)))
        model.samples_observed = int(data.get("samples_observed", 0))
        model.worker_speed = WorkerCostModel.from_dict(
            data.get("worker_speed") or {}
        )
        for scene, scene_data in (data.get("scenes") or {}).items():
            model._scenes[scene] = FrameComplexityModel.from_dict(scene_data)
        model._scenes.setdefault(
            DEFAULT_SCENE, FrameComplexityModel(model.alpha)
        )
        return model

    def save(self, path: str | Path) -> Path:
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        # Atomic: a reader (a resuming master) must never see a torn file.
        tmp = path.with_name(path.name + ".tmp")
        tmp.write_text(json.dumps(self.to_dict(), indent=1), encoding="utf-8")
        tmp.replace(path)
        return path

    @classmethod
    def load(cls, path: str | Path) -> "JointCostModel":
        return cls.from_dict(
            json.loads(Path(path).read_text(encoding="utf-8"))
        )


def load_model_snapshot(path: str | Path) -> JointCostModel | None:
    """Load a model snapshot, degrading to None (cold start) with a loud
    warning on a missing or rotted file — the master must come up (and
    re-learn online) regardless. The single definition of the degrade
    semantics: TRC_COST_MODEL loading, resume restore, and the serve
    service's restart snapshot all go through here."""
    path = Path(path)
    if not path.is_file():
        return None
    try:
        model = JointCostModel.load(path)
    except Exception as e:  # noqa: BLE001 - degrade to cold start
        logger.warning(
            "Cost model snapshot %s could not be loaded (%s); "
            "cold-starting.",
            path,
            e,
        )
        return None
    logger.info(
        "Cost model loaded from %s (%d samples, %d scene(s)).",
        path,
        model.samples_observed,
        len(model.scenes()),
    )
    return model


def save_model_snapshot(
    model: JointCostModel, path: str | Path
) -> Path | None:
    """Snapshot a model; returns None (with a warning) on failure —
    persistence must never fail a completed run. Cold models are skipped:
    an empty snapshot would overwrite a previously-learned one with
    nothing."""
    if not model.has_history():
        return None
    path = Path(path)
    try:
        model.save(path)
    except OSError as e:
        logger.warning("Could not snapshot the cost model to %s: %s", path, e)
        return None
    logger.info(
        "Cost model snapshotted to %s (%d samples).",
        path,
        model.samples_observed,
    )
    return path


def explicit_model_configured() -> bool:
    """True when ``TRC_COST_MODEL`` names an explicit startup model — the
    precedence gate snapshot-restore paths (resume, the serve service)
    consult so they never overwrite an operator-chosen model."""
    return bool((env_str("TRC_COST_MODEL") or "").strip())


def load_cost_model_from_env() -> JointCostModel | None:
    """The ``TRC_COST_MODEL`` startup model, or None (cold start)."""
    path = (env_str("TRC_COST_MODEL") or "").strip()
    if not path:
        return None
    model = load_model_snapshot(path)
    if model is None and not Path(path).is_file():
        logger.warning("TRC_COST_MODEL=%s does not exist; cold-starting.", path)
    return model


# -- offline training --------------------------------------------------------


class TraceSample(NamedTuple):
    """One recorded unit render: the offline trainer's input row."""

    worker_id: int
    frame_index: int
    seconds: float
    scene: str = DEFAULT_SCENE
    pixel_fraction: float = 1.0


def _worker_id_from_process_name(name: str) -> int | None:
    """``worker-<8 hex>`` (obs export convention) -> the worker id int."""
    prefix, _, suffix = name.partition("-")
    if prefix != "worker" or not suffix:
        return None
    try:
        return int(suffix.split("-")[0], 16)
    except ValueError:
        return None


def samples_from_cluster_trace(
    document: dict[str, Any], *, scene: str = DEFAULT_SCENE
) -> list[TraceSample]:
    """Per-unit render samples from a merged cluster timeline.

    Walks the worker process rows' ``render`` phase spans (worker/queue.py
    emits one per unit, args carrying ``frame`` and optionally ``tile``)
    and returns one ``TraceSample`` each. Tile pixel fractions are
    recovered as ``1 / tiles_seen`` — the grid itself never rides the
    trace, but an even-split grid's tiles differ by at most a pixel per
    axis, so the count is the fraction.
    """
    events = document.get("traceEvents")
    if not isinstance(events, list):
        return []
    process_names: dict[Any, str] = {}
    for event in events:
        if (
            isinstance(event, dict)
            and event.get("ph") == "M"
            and event.get("name") == "process_name"
        ):
            name = (event.get("args") or {}).get("name")
            if isinstance(name, str):
                process_names[event.get("pid")] = name
    raw: list[tuple[int, int, int | None, float]] = []
    tiles_seen: set[int] = set()
    for event in events:
        if not isinstance(event, dict) or event.get("ph") != "X":
            continue
        if event.get("name") != "render":
            continue
        worker_id = _worker_id_from_process_name(
            process_names.get(event.get("pid"), "")
        )
        if worker_id is None:
            continue
        args = event.get("args") or {}
        frame = args.get("frame")
        duration_us = event.get("dur")
        if not isinstance(frame, int) or not isinstance(duration_us, (int, float)):
            continue
        tile = args.get("tile") if isinstance(args.get("tile"), int) else None
        if tile is not None:
            tiles_seen.add(tile)
        raw.append((worker_id, frame, tile, float(duration_us) / 1e6))
    tile_fraction = 1.0 / max(1, len(tiles_seen))
    return [
        TraceSample(
            worker_id=worker_id,
            frame_index=frame,
            seconds=max(1e-6, seconds),
            scene=scene,
            pixel_fraction=tile_fraction if tile is not None else 1.0,
        )
        for worker_id, frame, tile, seconds in raw
    ]


def samples_from_statistics(
    statistics: dict[str, Any], *, scene: str = DEFAULT_SCENE
) -> list[TraceSample]:
    """Coarse per-worker speed priors from a ``statistics.json``.

    The ``critical_path`` sections carry per-worker median processing
    times (analysis/critical_path.straggler_scores) but no per-frame
    breakdown, so each worker contributes ONE flat sample at frame 0 —
    enough to warm the speed table, not the complexity curve. Prefer
    ``samples_from_cluster_trace`` when the merged timeline is available.
    """
    samples: list[TraceSample] = []
    for section in (statistics.get("critical_path") or {}).values():
        if not isinstance(section, dict):
            continue
        for label, entry in (section.get("workers") or {}).items():
            if not isinstance(entry, dict):
                continue
            p50 = entry.get("processing_p50_s")
            worker_id = _worker_id_from_process_name(f"worker-{label}")
            if worker_id is None:
                worker_id = _worker_id_from_process_name(str(label))
            if worker_id is None or not isinstance(p50, (int, float)) or p50 <= 0:
                continue
            samples.append(
                TraceSample(
                    worker_id=worker_id,
                    frame_index=0,
                    seconds=float(p50),
                    scene=scene,
                )
            )
    return samples


def fit_cost_model(
    samples: Iterable[TraceSample],
    *,
    alpha: float = DEFAULT_COST_EMA_ALPHA,
    sweeps: int = 4,
    curve_degree: int = 3,
    ridge_lambda: float = 1e-3,
) -> JointCostModel:
    """Fit a ``JointCostModel`` offline from recorded samples.

    Several alternating EMA sweeps converge the speed/complexity
    decomposition (the same update rule the online path uses, so the
    trained model is bit-compatible with online refinement), then a ridge
    polynomial (``ComplexityCurve``) is fit per scene over the
    speed-normalized times and attached as the out-of-support prior.
    """
    samples = list(samples)
    model = JointCostModel(alpha)
    if not samples:
        return model
    for _sweep in range(max(1, sweeps)):
        for sample in samples:
            model.observe(
                sample.worker_id,
                sample.frame_index,
                sample.seconds,
                scene=sample.scene,
                pixel_fraction=sample.pixel_fraction,
            )
    # samples_observed should reflect distinct recorded renders, not the
    # convergence sweeps.
    model.samples_observed = len(samples)
    per_scene: dict[str, tuple[list[int], list[float]]] = {}
    for sample in samples:
        speed = max(1e-6, model.worker_speed.predict(sample.worker_id))
        frames, values = per_scene.setdefault(sample.scene, ([], []))
        frames.append(sample.frame_index)
        values.append(
            sample.seconds / max(1e-9, sample.pixel_fraction) / speed
        )
    for scene, (frames, values) in per_scene.items():
        if len(set(frames)) < 2:
            continue  # a flat scene needs no curve
        model.complexity_model(scene).curve = ComplexityCurve.fit(
            frames, values, degree=curve_degree, ridge_lambda=ridge_lambda
        )
    return model


# -- online service ----------------------------------------------------------


class CostModelService:
    """The master's shared cost-model instance + its online feed.

    One per master process: every strategy loop (tpu-batch, the
    speculation loop, the multi-job scheduler tick) calls ``ingest`` to
    drain worker completion observations — each observation is consumed
    exactly once no matter how many loops tick, because draining is
    destructive and the master records exactly one observation per unit
    per job generation (the winning result's; duplicates and errored
    results never produce one) — and reads predictions off the shared
    model. Prediction error is accounted BEFORE the observation updates
    the model (``sched_cost_model_abs_error_seconds``) so the histogram
    measures what the scheduler actually acted on.
    """

    PREDICTION_LOG_LIMIT = 4096

    def __init__(
        self,
        model: JointCostModel | None = None,
        *,
        alpha: float = DEFAULT_COST_EMA_ALPHA,
        metrics: "MetricsRegistry | None" = None,
    ) -> None:
        self.model = model if model is not None else JointCostModel(alpha)
        self.metrics = metrics
        # Recent (predicted, actual) pairs for the live prediction view.
        self.prediction_log: deque[dict[str, Any]] = deque(
            maxlen=self.PREDICTION_LOG_LIMIT
        )

    @staticmethod
    def scene_key(job: "BlenderJob | None") -> str:
        """Scene identity = the project file path (stable across runs)."""
        return job.project_file_path if job is not None else DEFAULT_SCENE

    def predict_unit_seconds(
        self, worker_id: int, unit: WorkUnit, job: "BlenderJob | None"
    ) -> float:
        grid = job.tile_grid if job is not None else None
        return self.model.predict_unit_seconds(
            worker_id,
            unit.frame_index,
            scene=self.scene_key(job),
            pixel_fraction=unit_pixel_fraction(unit, grid),
        )

    def ingest(
        self,
        workers: Iterable["WorkerHandle"],
        job_for: Callable[[str | None], "BlenderJob | None"] | None = None,
    ) -> int:
        """Drain + fold every worker's fresh completion observations.

        ``job_for(job_name)`` resolves the owning job (scene key + tile
        grid); None prices everything as the default scene's whole
        frames. Returns how many observations were folded in.
        """
        folded = 0
        for worker in workers:
            for job_name, unit, seconds in worker.drain_completion_observations():
                job = job_for(job_name) if job_for is not None else None
                scene = self.scene_key(job)
                fraction = unit_pixel_fraction(
                    unit, job.tile_grid if job is not None else None
                )
                predicted: float | None = None
                if self.model.worker_speed.has_history(worker.worker_id):
                    predicted = self.model.predict_unit_seconds(
                        worker.worker_id,
                        unit.frame_index,
                        scene=scene,
                        pixel_fraction=fraction,
                    )
                    if self.metrics is not None:
                        self.metrics.histogram(
                            "sched_cost_model_abs_error_seconds",
                            "Absolute error of the cost model's per-unit "
                            "render-time prediction at observation time",
                        ).observe(abs(predicted - seconds))
                self.model.observe(
                    worker.worker_id,
                    unit.frame_index,
                    seconds,
                    scene=scene,
                    pixel_fraction=fraction,
                )
                self.prediction_log.append(
                    {
                        "worker": worker.worker_id,
                        "job": job_name,
                        "frame": unit.frame_index,
                        "tile": unit.tile,
                        "predicted_s": predicted,
                        "actual_s": seconds,
                    }
                )
                folded += 1
        return folded

    def prediction_view(self) -> dict[str, Any]:
        """Live predicted-vs-actual summary (cluster_view ``prediction``)."""
        pairs = [
            (entry["predicted_s"], entry["actual_s"])
            for entry in self.prediction_log
            if entry["predicted_s"] is not None
        ]
        out: dict[str, Any] = {
            "samples_observed": self.model.samples_observed,
            "scenes": len(self.model.scenes()),
            "predictions": len(pairs),
        }
        if pairs:
            errors = sorted(abs(p - a) for p, a in pairs)
            out["abs_error_mean_s"] = sum(errors) / len(errors)
            out["abs_error_p50_s"] = errors[len(errors) // 2]
            out["abs_error_max_s"] = errors[-1]
        return out


# -- CLI ----------------------------------------------------------------------


def main(argv: list[str] | None = None) -> int:
    """Offline trainer: merged cluster trace(s)/statistics.json -> model."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="trc-cost-model",
        description="Fit a predictive-scheduling cost model from recorded "
        "cluster traces (load it at master start via TRC_COST_MODEL).",
    )
    parser.add_argument(
        "inputs",
        nargs="+",
        help="Merged *_cluster_trace-events.json files and/or "
        "statistics.json files.",
    )
    parser.add_argument("-o", "--output", required=True)
    parser.add_argument("--scene", default=DEFAULT_SCENE)
    parser.add_argument("--alpha", type=float, default=DEFAULT_COST_EMA_ALPHA)
    args = parser.parse_args(argv)
    samples: list[TraceSample] = []
    for path in args.inputs:
        document = json.loads(Path(path).read_text(encoding="utf-8"))
        if isinstance(document, dict) and "traceEvents" in document:
            found = samples_from_cluster_trace(document, scene=args.scene)
        else:
            found = samples_from_statistics(document, scene=args.scene)
        print(f"{path}: {len(found)} sample(s)")
        samples.extend(found)
    model = fit_cost_model(samples, alpha=args.alpha)
    model.save(args.output)
    print(
        f"Wrote {args.output}: {model.samples_observed} samples, "
        f"{len(model.scenes())} scene(s)."
    )
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
