"""Multi-job scheduler: admission queue, weighted fair-share, preemptive
job lifecycle over one shared worker pool.

Public surface:

- ``JobManager`` / ``SchedulerConfig`` (sched/manager.py) — the service;
- ``JobSpec`` / ``JobRun`` + job-state constants (sched/models.py);
- ``fair_share`` (sched/fair_share.py) — the pure scheduling policy;
- ``ControlServer`` / ``control_request`` (sched/control.py) — the
  JSON-lines control plane ``python -m tpu_render_cluster.sched.submit``
  talks to.
"""

from tpu_render_cluster.sched.control import (
    ControlServer,
    control_request,
    control_request_sync,
    handle_request,
)
from tpu_render_cluster.sched.manager import JobManager, SchedulerConfig
from tpu_render_cluster.sched.models import (
    JOB_CANCELLED,
    JOB_FINISHED,
    JOB_QUEUED,
    JOB_RUNNING,
    JOB_STATES,
    JobRun,
    JobSpec,
)

__all__ = [
    "ControlServer",
    "JobManager",
    "JobRun",
    "JobSpec",
    "JOB_CANCELLED",
    "JOB_FINISHED",
    "JOB_QUEUED",
    "JOB_RUNNING",
    "JOB_STATES",
    "SchedulerConfig",
    "control_request",
    "control_request_sync",
    "handle_request",
]
