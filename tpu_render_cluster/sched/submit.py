"""Client CLI for the scheduler control plane.

::

    python -m tpu_render_cluster.sched.submit --host H --controlPort P \\
        submit job.toml [--weight 3] [--priority 1]
    python -m tpu_render_cluster.sched.submit ... status [--job JOB_ID]
    python -m tpu_render_cluster.sched.submit ... cancel JOB_ID
    python -m tpu_render_cluster.sched.submit ... drain
    python -m tpu_render_cluster.sched.submit ... alerts

Prints the control plane's JSON response; exits non-zero when the server
answers ``ok: false`` (or is unreachable), so scripts can chain on it.
"""

from __future__ import annotations

import argparse
import json
import sys

from tpu_render_cluster.jobs.models import BlenderJob
from tpu_render_cluster.sched.control import control_request_sync


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="trc-submit", description="Scheduler control-plane client"
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument(
        "--controlPort", dest="control_port", type=int, default=9902
    )
    parser.add_argument("--timeout", type=float, default=30.0)
    sub = parser.add_subparsers(dest="command", required=True)

    submit = sub.add_parser("submit", help="Submit a job TOML")
    submit.add_argument("job_file_path")
    submit.add_argument("--weight", type=float, default=1.0)
    submit.add_argument("--priority", type=int, default=0)

    status = sub.add_parser("status", help="Scheduler (or one job's) status")
    status.add_argument("--job", dest="job_id", default=None)

    cancel = sub.add_parser("cancel", help="Cancel a queued/running job")
    cancel.add_argument("job_id")

    sub.add_parser("drain", help="Stop admitting; exit when idle")
    sub.add_parser(
        "alerts", help="SLO alert log + live per-job attainment/burn view"
    )
    return parser


def _build_request(args: argparse.Namespace) -> dict:
    if args.command == "submit":
        job = BlenderJob.load_from_file(args.job_file_path)
        return {
            "op": "submit",
            "spec": {
                "job": job.to_dict(),
                "weight": args.weight,
                "priority": args.priority,
            },
        }
    if args.command == "status":
        request: dict = {"op": "status"}
        if args.job_id is not None:
            request["job_id"] = args.job_id
        return request
    if args.command == "cancel":
        return {"op": "cancel", "job_id": args.job_id}
    if args.command == "alerts":
        return {"op": "alerts"}
    return {"op": "drain"}


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        request = _build_request(args)
    except (OSError, ValueError) as e:
        print(json.dumps({"ok": False, "error": str(e)}))
        return 2
    try:
        response = control_request_sync(
            args.host, args.control_port, request, timeout=args.timeout
        )
    except (OSError, ValueError, ConnectionError) as e:
        print(json.dumps({"ok": False, "error": f"control plane unreachable: {e}"}))
        return 2
    print(json.dumps(response, indent=2))
    return 0 if response.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())
