"""Pure fair-share arithmetic: slot targets, dispatch picks, preemption.

All functions are side-effect-free over plain inputs so the scheduling
policy is unit-testable without a cluster (the same design rule as the
steal-candidate selectors in master/strategies.py).

Model: the cluster offers ``total_slots`` in-flight frame slots (live
workers x per-worker target queue size). Jobs are split into strict
priority classes (higher ``priority`` first); within a class each job's
target is its weight-proportional share of the slots the class received,
capped by the job's *demand* (it can never use more slots than it has
frames left), with the leftover water-filling down to lower classes.

Dispatch follows the classic weighted-fair-queueing rule — serve the
runnable job with the smallest normalized load ``load / weight``, where
load is the job's in-flight work in PREDICTED SECONDS when the cost model
(sched/cost_model.py) has priced the inputs and the in-flight unit count
before any history exists — which converges to the weight-proportional
allocation without ever needing the target values; the targets exist for
preemption decisions and observability (``sched_job_share`` gauges, the
acceptance criterion's achieved-vs-target comparison). Targets and
preemption stay in SLOT units: slots are what the pool physically offers
(worker queue positions), and a seconds-denominated target would preempt
a job for merely holding slow units it cannot help holding.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

# One whole slot of slack before anybody preempts: fractional targets
# (e.g. 4.5 vs 1.5 on 6 slots) must not cause steady-state thrash.
PREEMPTION_SLACK_SLOTS = 1.0
_EPS = 1e-9


@dataclass(frozen=True)
class JobShareInput:
    """One running job's instantaneous scheduling inputs.

    ``in_flight_cost`` is the job's in-flight work in PREDICTED SECONDS
    (the cost model's per-unit predictions summed over its queued +
    rendering units). When present, the WFQ dispatch pick meters load by
    it instead of the unit count, so a job holding one predicted-slow
    unit is not treated as lighter than a job holding three fast ones.
    Callers must supply it uniformly across one tick's inputs (all jobs
    or none) — mixing seconds with counts would compare incommensurable
    loads; ``pick_job_to_dispatch`` falls back to the count for any job
    missing it.
    """

    job_id: str
    weight: float
    priority: int
    in_flight: int
    pending: int
    in_flight_cost: float | None = None

    @property
    def demand(self) -> int:
        """Max slots this job can usefully hold right now."""
        return self.in_flight + self.pending

    @property
    def load(self) -> float:
        """The WFQ load measure: predicted seconds when known, else units."""
        return (
            self.in_flight_cost
            if self.in_flight_cost is not None
            else float(self.in_flight)
        )


def compute_slot_targets(
    jobs: Sequence[JobShareInput], total_slots: float
) -> dict[str, float]:
    """Per-job target in-flight slots (fractional).

    Strict priority: classes are served highest-first, each consuming up
    to its total demand. Within a class, weighted water-filling: each job
    gets its weight-proportional share of the class's slots, demand-capped
    jobs are clamped and their surplus redistributed among the rest.
    """
    targets = {job.job_id: 0.0 for job in jobs}
    remaining = max(0.0, float(total_slots))
    for priority in sorted({job.priority for job in jobs}, reverse=True):
        if remaining <= _EPS:
            break
        unsatisfied = {
            job.job_id: job
            for job in jobs
            if job.priority == priority and job.demand > 0
        }
        while unsatisfied and remaining > _EPS:
            total_weight = sum(job.weight for job in unsatisfied.values())
            clamped_id = None
            for job_id, job in unsatisfied.items():
                grant = remaining * job.weight / total_weight
                if job.demand <= grant + _EPS:
                    clamped_id = job_id
                    break
            if clamped_id is None:
                # Nobody is demand-capped: the proportional split stands.
                for job_id, job in unsatisfied.items():
                    targets[job_id] = remaining * job.weight / total_weight
                remaining = 0.0
                break
            job = unsatisfied.pop(clamped_id)
            targets[clamped_id] = float(job.demand)
            remaining -= job.demand
    return targets


def pick_job_to_dispatch(
    jobs: Sequence[JobShareInput],
) -> str | None:
    """The job the next free slot should serve, or None when nothing is
    runnable (no pending frames anywhere).

    Highest priority class with pending work wins outright; within it,
    the weighted-fair-queueing pick: minimal ``load / weight`` — load in
    predicted seconds when the cost model priced the inputs
    (``in_flight_cost``), else the in-flight unit count — ties broken by
    input order (submit order, so the allocation is deterministic).
    """
    runnable = [job for job in jobs if job.pending > 0]
    if not runnable:
        return None
    top = max(job.priority for job in runnable)
    best: JobShareInput | None = None
    for job in runnable:
        if job.priority != top:
            continue
        if best is None or job.load / job.weight < best.load / best.weight - _EPS:
            best = job
    assert best is not None
    return best.job_id


def pick_preemption(
    jobs: Sequence[JobShareInput],
    targets: dict[str, float],
) -> tuple[str, str] | None:
    """(over-share job, starved job) when preempting one slot is justified.

    A job is *starved* when it has pending frames and sits at least one
    whole slot under its target; a job is *over* when it holds at least
    ``PREEMPTION_SLACK_SLOTS`` more than its target. Both must exist
    simultaneously — otherwise natural completion drains the imbalance
    and preempting would only waste a queued frame's wait time. The most
    over and the most starved are paired (one preemption per call; the
    caller rate-limits per tick).
    """
    starved: JobShareInput | None = None
    over: JobShareInput | None = None
    for job in jobs:
        target = targets.get(job.job_id, 0.0)
        deficit = target - job.in_flight
        surplus = job.in_flight - target
        if job.pending > 0 and deficit >= 1.0 - _EPS:
            if starved is None or deficit > targets.get(starved.job_id, 0.0) - starved.in_flight:
                starved = job
        if surplus >= PREEMPTION_SLACK_SLOTS - _EPS:
            if over is None or surplus > over.in_flight - targets.get(over.job_id, 0.0):
                over = job
    if starved is None or over is None or starved.job_id == over.job_id:
        return None
    return over.job_id, starved.job_id
