"""In-process cluster runner: one master + N workers over localhost.

Every run uses the full production stack — ClusterManager's accepting
server, the 3-step handshake, heartbeats, and the real distribution
strategies — only colocated in a single asyncio loop, exactly like the
integration tests. Traces are persisted with the same writer the master
CLI uses, so the output is indistinguishable from a multi-host run
(reference: master/src/main.rs:26-338 persistence path).
"""

from __future__ import annotations

import asyncio
import os
from datetime import datetime
from pathlib import Path

from tpu_render_cluster.jobs.models import BlenderJob
from tpu_render_cluster.master.cluster import ClusterManager
from tpu_render_cluster.master.persist import (
    parse_worker_traces,
    save_processed_results,
    save_raw_traces,
)
from tpu_render_cluster.obs import (
    MetricsRegistry,
    export_chrome_trace,
    export_cluster_trace,
    merge_wire,
    write_metrics_snapshot,
)
from tpu_render_cluster.protocol.messages import worker_id_to_string
from tpu_render_cluster.traces.master_trace import MasterTrace
from tpu_render_cluster.traces.worker_trace import WorkerTrace
from tpu_render_cluster.worker.backends.base import RenderBackend
from tpu_render_cluster.worker.runtime import Worker


async def _run(
    job: BlenderJob,
    backends: list[RenderBackend],
    *,
    manager_factory=None,
    worker_factory=None,
    on_cluster_started=None,
    worker_grace: float | None = None,
    allow_worker_failures: bool = False,
):
    """Run one in-process cluster job.

    The optional hooks are the chaos harness's seams (all default to the
    plain production path):

    - ``manager_factory(job)`` / ``worker_factory(slot, port, backend)``
      construct the components (e.g. with fault-injecting connection
      wrappers and per-slot registries);
    - ``on_cluster_started(manager, workers, worker_tasks)`` runs once the
      tasks exist — where fault watchdogs attach and start;
    - ``worker_grace`` bounds how long to wait for worker tasks after the
      master finishes; leftovers (crashed/hung workers that will never
      exit) are cancelled instead of hanging the harness;
    - ``allow_worker_failures`` tolerates worker tasks that died of
      injected faults; without it the first worker exception re-raises.
    """
    # A fresh registry per run: harness callers (tests, sweep scripts)
    # run many jobs in one process, and per-run artifacts must not
    # accumulate counts across runs the way the CLI's process-global
    # default (one job per process) is allowed to.
    if manager_factory is not None:
        manager = manager_factory(job)
    else:
        manager = ClusterManager("127.0.0.1", 0, job, metrics=MetricsRegistry())
    server_task = asyncio.create_task(manager.initialize_server_and_run_job())
    while manager._server is None:
        if server_task.done():
            # Startup failed (e.g. port bind); await to surface the real
            # exception instead of spinning until the outer timeout.
            await server_task
            raise RuntimeError("master server task exited before startup")
        await asyncio.sleep(0.01)
    # Fresh per-worker registries too: colocated workers must not share
    # the process-global registry or their heartbeat payloads (and the
    # per-worker snapshots in the metrics artifact) would double-count.
    if worker_factory is not None:
        workers = [
            worker_factory(slot, manager.port, backend)
            for slot, backend in enumerate(backends)
        ]
    else:
        workers = [
            Worker("127.0.0.1", manager.port, backend, metrics=MetricsRegistry())
            for backend in backends
        ]
    worker_tasks = [
        asyncio.create_task(w.connect_and_run_to_job_completion()) for w in workers
    ]
    if on_cluster_started is not None:
        await on_cluster_started(manager, workers, worker_tasks)
    master_trace, worker_traces = await server_task
    if allow_worker_failures and worker_grace is None:
        # Tolerating failures implies tolerating workers that never exit
        # (a hung/killed worker's task has no reason to finish): an
        # unbounded wait here would hang the harness, so failure-tolerant
        # runs always get a finite reap window.
        worker_grace = 60.0
    if worker_grace is None and not allow_worker_failures:
        await asyncio.gather(*worker_tasks)
    else:
        _done, pending = await asyncio.wait(
            worker_tasks, timeout=worker_grace
        )
        for task in pending:
            task.cancel()
        results = await asyncio.gather(*worker_tasks, return_exceptions=True)
        if not allow_worker_failures:
            for result in results:
                if isinstance(result, Exception):
                    raise result
    return master_trace, worker_traces, manager, workers


async def _run_multi_job(
    specs,
    backends: list[RenderBackend],
    *,
    manager_factory=None,
    worker_factory=None,
    on_cluster_started=None,
    driver=None,
    worker_grace: float | None = None,
    allow_worker_failures: bool = False,
):
    """Run the multi-job scheduler service over an in-process cluster.

    The service analog of ``_run``: one ``sched.JobManager`` accepting
    real localhost WebSockets, N workers, every ``JobSpec`` in ``specs``
    submitted up front, then a drain request — ``serve()`` returns once
    every job finished. The chaos seams match ``_run``'s
    (``manager_factory()`` / ``worker_factory(slot, port, backend)`` /
    ``on_cluster_started``); ``driver(manager, workers)`` additionally
    runs after submission so tests can exercise the lifecycle API
    (cancel mid-run, late submissions, status polls) against the live
    service before the drain lands.
    """
    from tpu_render_cluster.sched.manager import JobManager

    if manager_factory is not None:
        manager = manager_factory()
    else:
        manager = JobManager("127.0.0.1", 0, metrics=MetricsRegistry())
    serve_task = asyncio.create_task(manager.serve())
    while manager._server is None:
        if serve_task.done():
            await serve_task
            raise RuntimeError("scheduler serve task exited before startup")
        await asyncio.sleep(0.01)
    if worker_factory is not None:
        workers = [
            worker_factory(slot, manager.port, backend)
            for slot, backend in enumerate(backends)
        ]
    else:
        workers = [
            Worker("127.0.0.1", manager.port, backend, metrics=MetricsRegistry())
            for backend in backends
        ]
    worker_tasks = [
        asyncio.create_task(w.connect_and_run_to_job_completion()) for w in workers
    ]
    if on_cluster_started is not None:
        await on_cluster_started(manager, workers, worker_tasks)
    job_ids = [manager.submit(spec) for spec in specs]
    if driver is not None:
        await driver(manager, workers)
    manager.request_drain()
    worker_traces = await serve_task
    if allow_worker_failures and worker_grace is None:
        worker_grace = 60.0
    if worker_grace is None and not allow_worker_failures:
        await asyncio.gather(*worker_tasks)
    else:
        _done, pending = await asyncio.wait(worker_tasks, timeout=worker_grace)
        for task in pending:
            task.cancel()
        results = await asyncio.gather(*worker_tasks, return_exceptions=True)
        if not allow_worker_failures:
            for result in results:
                if isinstance(result, Exception):
                    raise result
    return worker_traces, job_ids, manager, workers


def run_local_multi_job(
    specs,
    backends: list[RenderBackend],
    *,
    timeout: float = 600.0,
    driver=None,
):
    """Run jobs through the scheduler service on an in-process cluster.

    Returns ``(worker_traces, job_ids, manager, workers)`` — the manager
    is handed back live (post-shutdown) so callers can audit per-job
    states, ledgers, and the scheduler view.
    """
    return asyncio.run(
        asyncio.wait_for(_run_multi_job(specs, backends, driver=driver), timeout)
    )


def _run_local_job_full(
    job: BlenderJob, backends: list[RenderBackend], timeout: float
) -> tuple[MasterTrace, list[tuple[str, WorkerTrace]], ClusterManager, list[Worker]]:
    return asyncio.run(asyncio.wait_for(_run(job, backends), timeout))


def run_local_job(
    job: BlenderJob,
    backends: list[RenderBackend],
    *,
    timeout: float = 600.0,
) -> tuple[MasterTrace, list[tuple[str, WorkerTrace]]]:
    """Run one job on an in-process cluster; returns (master trace, worker traces)."""
    master_trace, worker_traces, _, _ = _run_local_job_full(job, backends, timeout)
    return master_trace, worker_traces


def _process_roofline() -> dict:
    """The process-global kernel profiler's view (empty dict when nothing
    was profiled — the snapshot key is always present so consumers can
    distinguish 'no profiling' from 'old artifact')."""
    from tpu_render_cluster.obs.profiling import get_profiler

    return get_profiler().view()


def save_obs_artifacts(
    prefix_path: Path, manager: ClusterManager, workers: list[Worker]
) -> tuple[Path, Path, Path]:
    """Write ``<prefix>_trace-events.json`` + ``<prefix>_metrics.json``
    + ``<prefix>_cluster_trace-events.json``.

    The trace-event file merges the master's span tracer with every
    worker's (one Perfetto process row each) and loads directly in
    https://ui.perfetto.dev or chrome://tracing. The metrics file carries
    the master registry snapshot, the live cluster view, each worker's
    full registry snapshot, and their ``merge_wire`` aggregation —
    exactly what a multi-host master assembles from heartbeat payloads,
    but collected in-process after the run. The cluster trace is the
    CAUSAL timeline: the span events each worker piggybacked on its
    job-finished response, rebased onto the master clock by the heartbeat
    clock-offset estimates, pids deduplicated, with flow arrows linking
    every frame's assign span to its worker phases and result span.
    """
    from tpu_render_cluster.obs import get_registry, get_tracer

    # The process-global tracer rides along: render-path spans (e.g. the
    # wavefront driver's per-bounce wavefront_bounce spans with live
    # count / bucket / alive-fraction args) land in the same Perfetto
    # file as the master/worker rows. It is process-scoped and the
    # harness runs many jobs per process, so drain it after the export —
    # otherwise job N's file would re-export jobs 1..N-1's render spans.
    trace_path = export_chrome_trace(
        prefix_path.with_name(prefix_path.name + "_trace-events.json"),
        [manager.span_tracer] + [w.span_tracer for w in workers] + [get_tracer()],
    )
    get_tracer().clear()
    # The merged causal timeline goes through the same collection path a
    # multi-host master uses (span events shipped on job-finished, offsets
    # from the heartbeat estimator) — in-process the offsets are near zero,
    # but the machinery is identical.
    cluster_trace_path = export_cluster_trace(
        prefix_path.with_name(prefix_path.name + "_cluster_trace-events.json"),
        manager.cluster_timeline_processes(),
        extra_other_data=manager.timeline_other_data(),
    )
    worker_snapshots = {
        worker_id_to_string(w.worker_id): w.metrics.snapshot() for w in workers
    }
    metrics_path = write_metrics_snapshot(
        prefix_path.with_name(prefix_path.name + "_metrics.json"),
        manager.metrics,
        extra={
            **manager.cluster_view(),
            "workers": worker_snapshots,
            "workers_wire_merged": merge_wire(
                [w.metrics.to_wire() for w in workers]
            ),
            # Harness workers run with fresh per-run registries, but the
            # RENDER path (backend phase histograms, the wavefront
            # driver's occupancy series) reports into the process-global
            # registry — snapshot it too or those series never reach the
            # artifact. Process-scoped and CUMULATIVE across runs in one
            # harness process, so it is tagged with the pid: consumers
            # (analysis/obs_events.summarize_wavefront) keep only the
            # newest snapshot per pid instead of summing every file's
            # copy of the same counters.
            "process_metrics": {
                "pid": os.getpid(),
                "metrics": get_registry().snapshot(),
            },
            # Per-kernel roofline evidence (obs/profiling.py): like the
            # process registry, the profiler is process-global and
            # cumulative — summarize_roofline keeps newest-wins per
            # kernel key.
            "roofline": _process_roofline(),
            # Continuous-observability roll-up (obs/history.py): per-
            # counter increase/rate/trend and per-gauge envelopes over the
            # run's sampled window — the statistics.json `history` fold.
            "history": manager.history.summary_dict(),
        },
    )
    return trace_path, metrics_path, cluster_trace_path


def run_and_persist(
    job: BlenderJob,
    backends: list[RenderBackend],
    results_directory: str | Path,
    *,
    timeout: float = 600.0,
) -> Path:
    """Run and write ``*_raw-trace.json`` + processed results; returns the raw path.

    Also emits the obs artifacts next to them: ``*_trace-events.json``
    (Chrome trace-event spans for master, workers, and transport),
    ``*_metrics.json`` (metrics snapshot incl. frame-phase histograms),
    and ``*_cluster_trace-events.json`` (the merged clock-corrected causal
    timeline with per-frame flow arrows).
    """
    from tpu_render_cluster.ops import assignment as assignment_ops

    start = datetime.now()
    assignment_ops.reset_greedy_fallback_count()
    master_trace, worker_traces, manager, workers = _run_local_job_full(
        job, backends, timeout
    )
    results_directory = Path(results_directory)
    raw_path = save_raw_traces(start, job, results_directory, master_trace, worker_traces)
    save_obs_artifacts(
        raw_path.with_name(raw_path.name.replace("_raw-trace.json", "")),
        manager,
        workers,
    )
    performance = parse_worker_traces(worker_traces)
    save_processed_results(
        start,
        job,
        results_directory,
        performance,
        scheduler_stats={
            "auction_greedy_fallbacks": assignment_ops.greedy_fallback_count(),
        },
    )
    return raw_path
