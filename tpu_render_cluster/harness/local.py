"""In-process cluster runner: one master + N workers over localhost.

Every run uses the full production stack — ClusterManager's accepting
server, the 3-step handshake, heartbeats, and the real distribution
strategies — only colocated in a single asyncio loop, exactly like the
integration tests. Traces are persisted with the same writer the master
CLI uses, so the output is indistinguishable from a multi-host run
(reference: master/src/main.rs:26-338 persistence path).
"""

from __future__ import annotations

import asyncio
from datetime import datetime
from pathlib import Path

from tpu_render_cluster.jobs.models import BlenderJob
from tpu_render_cluster.master.cluster import ClusterManager
from tpu_render_cluster.master.persist import (
    parse_worker_traces,
    save_processed_results,
    save_raw_traces,
)
from tpu_render_cluster.traces.master_trace import MasterTrace
from tpu_render_cluster.traces.worker_trace import WorkerTrace
from tpu_render_cluster.worker.backends.base import RenderBackend
from tpu_render_cluster.worker.runtime import Worker


async def _run(job: BlenderJob, backends: list[RenderBackend]):
    manager = ClusterManager("127.0.0.1", 0, job)
    server_task = asyncio.create_task(manager.initialize_server_and_run_job())
    while manager._server is None:
        if server_task.done():
            # Startup failed (e.g. port bind); await to surface the real
            # exception instead of spinning until the outer timeout.
            await server_task
            raise RuntimeError("master server task exited before startup")
        await asyncio.sleep(0.01)
    workers = [Worker("127.0.0.1", manager.port, backend) for backend in backends]
    worker_tasks = [
        asyncio.create_task(w.connect_and_run_to_job_completion()) for w in workers
    ]
    master_trace, worker_traces = await server_task
    await asyncio.gather(*worker_tasks)
    return master_trace, worker_traces


def run_local_job(
    job: BlenderJob,
    backends: list[RenderBackend],
    *,
    timeout: float = 600.0,
) -> tuple[MasterTrace, list[tuple[str, WorkerTrace]]]:
    """Run one job on an in-process cluster; returns (master trace, worker traces)."""
    return asyncio.run(asyncio.wait_for(_run(job, backends), timeout))


def run_and_persist(
    job: BlenderJob,
    backends: list[RenderBackend],
    results_directory: str | Path,
    *,
    timeout: float = 600.0,
) -> Path:
    """Run and write ``*_raw-trace.json`` + processed results; returns the raw path."""
    from tpu_render_cluster.ops import assignment as assignment_ops

    start = datetime.now()
    assignment_ops.reset_greedy_fallback_count()
    master_trace, worker_traces = run_local_job(job, backends, timeout=timeout)
    results_directory = Path(results_directory)
    raw_path = save_raw_traces(start, job, results_directory, master_trace, worker_traces)
    performance = parse_worker_traces(worker_traces)
    save_processed_results(
        start,
        job,
        results_directory,
        performance,
        scheduler_stats={
            "auction_greedy_fallbacks": assignment_ops.greedy_fallback_count(),
        },
    )
    return raw_path
