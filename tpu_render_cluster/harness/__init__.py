"""Experiment harness: in-process cluster runs + the recorded matrix.

The reference validates itself with SLURM runs on real clusters
(SURVEY.md §4); this package is the single-host counterpart — it runs a
real master + N real workers over localhost WebSockets inside one process
and persists reference-schema raw traces, so the measurement product
(analysis A5-A12) can be produced and regenerated anywhere.
"""

from tpu_render_cluster.harness.local import (
    run_and_persist,
    run_local_job,
    save_obs_artifacts,
)

__all__ = ["run_local_job", "run_and_persist", "save_obs_artifacts"]
