"""tpu_render_cluster — TPU-native distributed rendering framework.

A master/worker render farm with the capabilities of the reference render
cluster (see SURVEY.md): a 14-message WebSocket job protocol, pluggable
frame-distribution strategies (naive-fine, eager-naive-coarse, dynamic work
stealing, and the TPU cost-matrix `tpu-batch` scheduler), pluggable render
backends (Blender subprocess, pure-JAX/Pallas `tpu-raytrace` path tracer),
7-phase frame timing traces, and an analysis suite compatible with the
reference's raw-trace JSON schema.

Control-plane semantics follow the reference contract
(`/root/reference/shared/src/` et al., cited per-module); the implementation
is TPU-first: JAX/XLA/Pallas for compute and scheduling math, asyncio +
a C++ codec for the control plane.
"""

__version__ = "1.0.0"

# The protocol version exchanged during the handshake. The reference sends its
# crate version here (reference: shared/src/messages/handshake.rs:31-47).
PROTOCOL_VERSION = __version__
