"""Device-mesh parallelism: tile/sample/frame sharding with XLA collectives.

The reference scales by adding worker *processes* connected over WebSockets
(its only parallelism is the task farm — SURVEY.md §2.7). This package adds
the intra-worker dimension it never had: one worker drives an entire TPU
slice through ``jax.sharding.Mesh`` + ``shard_map``, with XLA collectives
(psum/all_gather over ICI) instead of socket traffic.
"""

from tpu_render_cluster.parallel.mesh import device_mesh, local_device_count

__all__ = ["device_mesh", "local_device_count"]
