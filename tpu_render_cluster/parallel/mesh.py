"""Mesh construction helpers."""

from __future__ import annotations

import jax
from jax.sharding import Mesh


def local_device_count() -> int:
    return len(jax.devices())


def device_mesh(
    n_devices: int | None = None, *, axis_name: str = "d"
) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` devices (default: all).

    Multi-host expansion: call ``jax.distributed.initialize()`` before this
    and the mesh spans the global device set (DCN between hosts, ICI within
    a slice) — same code path either way.
    """
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"Requested {n_devices} devices, have {len(devices)}."
            )
        devices = devices[:n_devices]
    import numpy as np

    return Mesh(np.array(devices), (axis_name,))
