"""Mesh construction helpers (single-host ICI and multi-host DCN).

The reference scales across hosts purely via the master/worker protocol
(one Blender process per SLURM task); this build additionally scales each
WORKER across hosts the TPU way: ``initialize_multihost`` brings up JAX's
distributed runtime (reference analog: the NCCL/MPI world the survey's
checklist names — here it is XLA collectives over DCN between hosts, ICI
within a slice, SURVEY.md §2.7/§5.8), after which ``device_mesh`` spans
the global device set and the sharded render paths
(parallel/sharded_render.py) work unchanged.
"""

from __future__ import annotations

import os

import jax
from jax.sharding import Mesh


def initialize_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Join (or skip) the multi-host JAX distributed runtime.

    Explicit arguments win; otherwise the standard environment is used
    (``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID``,
    or cloud auto-detection inside ``jax.distributed.initialize``). With no
    configuration at all this is a no-op returning False — the single-host
    path stays untouched. Returns True when the distributed runtime came
    up; after that ``jax.devices()`` is the GLOBAL device set and
    ``device_mesh`` spans hosts (DCN) as well as the local slice (ICI).
    """
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    env_processes = os.environ.get("JAX_NUM_PROCESSES")
    env_process_id = os.environ.get("JAX_PROCESS_ID")
    if num_processes is None and env_processes is not None:
        num_processes = int(env_processes)
    if process_id is None and env_process_id is not None:
        process_id = int(env_process_id)
    if (
        coordinator_address is None
        and num_processes is None
        and process_id is None
    ):
        return False  # single-host: nothing to join
    if coordinator_address is None or num_processes is None or process_id is None:
        # A partially-set triple is a launcher bug (e.g. the line exporting
        # JAX_COORDINATOR_ADDRESS dropped from a SLURM script): silently
        # coming up single-host would "work" with the cross-host mesh
        # never forming. Fail loudly instead.
        raise ValueError(
            "Multi-host configuration is incomplete: coordinator_address="
            f"{coordinator_address!r}, num_processes={num_processes!r}, "
            f"process_id={process_id!r} — set all three (flags or "
            "JAX_COORDINATOR_ADDRESS/JAX_NUM_PROCESSES/JAX_PROCESS_ID) "
            "or none."
        )
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def local_device_count() -> int:
    return len(jax.devices())


def device_mesh(
    n_devices: int | None = None, *, axis_name: str = "d"
) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` devices (default: all).

    Multi-host expansion: call ``jax.distributed.initialize()`` before this
    and the mesh spans the global device set (DCN between hosts, ICI within
    a slice) — same code path either way.
    """
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"Requested {n_devices} devices, have {len(devices)}."
            )
        devices = devices[:n_devices]
    import numpy as np

    return Mesh(np.array(devices), (axis_name,))
