"""Multi-device frame rendering via shard_map.

Three sharding modes, mirroring how a multi-chip worker can split render
work (the SP/DP analogs called for by SURVEY.md §2.7 / §5.7):

- ``render_frame_sharded(mode="tile")``: the image's row dimension is
  sharded — each device renders a horizontal band of the same frame
  (spatial decomposition; output is jointly sharded, gathered on host
  read);
- ``render_frame_sharded(mode="spp")``: every device renders the full
  frame with a decorrelated subset of samples and the results are
  averaged with a ``psum`` over ICI (sample decomposition — a true
  collective reduction);
- ``render_frames_batched``: a batch of frames is sharded one-per-device
  (the task-farm axis collapsed into the device mesh — highest
  throughput for animation). This is a separate function, not a
  ``render_frame_sharded`` mode, because its unit of work is a batch.

Wavefront composition: every mode here traces ``render_tile`` under
``shard_map``, so the HOST-DRIVEN wavefront driver (per-bounce device
sync + dynamically shrinking launch widths; render/compaction.py) cannot
run inside it. What composes instead is the IN-JIT half of compaction:
per shard, the integrator's deep-scene bounce loop sorts its OWN rays
dead-to-tail and hands the bounce kernel a live-count scalar, so each
device skips its all-dead tail blocks with static shapes — no
cross-device coordination, no recompiles, works under tile bands, spp
subsets, and frame batches alike. (Tile sharding even helps it: a band's
rays are spatially coherent, so their live sets collapse together.)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_render_cluster.parallel.mesh import device_mesh
from tpu_render_cluster.render.camera import scene_camera
from tpu_render_cluster.render.integrator import render_tile
from tpu_render_cluster.render.scene import build_scene


def _shard_map(fn, mesh, in_specs, out_specs):
    # check_vma=False: the integrator's scan carries start replicated and
    # become device-varying when axis_index feeds the RNG — intended here.
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )
    # jax < 0.5: shard_map lives in jax.experimental and the replication
    # check is spelled check_rep.
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    return _experimental_shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
    )


def render_frame_sharded(
    scene_name: str,
    frame_index: int,
    *,
    width: int = 512,
    height: int = 512,
    samples: int = 8,
    max_bounces: int = 4,
    mode: str = "tile",
    n_devices: int | None = None,
) -> jnp.ndarray:
    """Render one frame across the local mesh; returns [H, W, 3] linear."""
    mesh = device_mesh(n_devices)
    n = mesh.devices.size
    scene = build_scene(scene_name, frame_index)
    camera = scene_camera(scene_name, frame_index)
    from tpu_render_cluster.render.integrator import resolve_bvh_config
    from tpu_render_cluster.render.mesh import scene_mesh_set

    # BVH env tiers resolve HERE (untraced) and ride the traced closures
    # as captured statics — the env-tiers contract.
    _tlas, bvh_quant, bvh_builder, bvh_wide = resolve_bvh_config()
    mesh_set = scene_mesh_set(scene_name, frame_index, bvh_builder, bvh_wide)
    frame = jnp.asarray(frame_index, jnp.float32)

    if mode == "tile":
        if height % n != 0:
            raise ValueError(f"height {height} not divisible by {n} devices.")
        rows_per_device = height // n

        def render_band(scene, camera, frame):
            band_index = jax.lax.axis_index("d")
            y0 = band_index * rows_per_device
            return render_tile(
                scene,
                camera,
                frame,
                y0,
                0,
                width=width,
                height=height,
                tile_height=rows_per_device,
                tile_width=width,
                samples=samples,
                max_bounces=max_bounces,
                mesh=mesh_set,
                quant=bvh_quant,
            )

        sharded = _shard_map(
            render_band,
            mesh=mesh,
            in_specs=(P(), P(), P()),
            out_specs=P("d", None, None),
        )
        return sharded(scene, camera, frame)

    if mode == "spp":
        if samples % n != 0:
            raise ValueError(f"samples {samples} not divisible by {n} devices.")
        samples_per_device = samples // n

        def render_subset(scene, camera, frame):
            device_index = jax.lax.axis_index("d")
            # Decorrelate: fold the device index into the frame-derived seed
            # by offsetting the y0 RNG ingredient with a device-unique tag.
            image = render_tile(
                scene,
                camera,
                frame,
                0,
                device_index * 131071,  # x0 only feeds the RNG here
                width=width,
                height=height,
                tile_height=height,
                tile_width=width,
                samples=samples_per_device,
                max_bounces=max_bounces,
                mesh=mesh_set,
                quant=bvh_quant,
            )
            return jax.lax.psum(image, "d") / n

        sharded = _shard_map(
            render_subset,
            mesh=mesh,
            in_specs=(P(), P(), P()),
            out_specs=P(),
        )
        return sharded(scene, camera, frame)

    raise ValueError(f"Unknown sharding mode: {mode!r}")


def render_frames_batched(
    scene_name: str,
    frame_indices,
    *,
    width: int = 256,
    height: int = 256,
    samples: int = 4,
    max_bounces: int = 4,
    n_devices: int | None = None,
) -> jnp.ndarray:
    """Render a batch of frames, one shard of the batch per device.

    The frame batch must be divisible by the device count. Scene build is
    vmapped on device; the only host work is the final gather.
    Returns [B, H, W, 3] linear radiance.
    """
    mesh = device_mesh(n_devices)
    n = mesh.devices.size
    frames = jnp.asarray(frame_indices, jnp.float32)
    if frames.shape[0] % n != 0:
        raise ValueError(f"Batch {frames.shape[0]} not divisible by {n} devices.")

    from tpu_render_cluster.render.integrator import resolve_bvh_config

    _tlas, bvh_quant, bvh_builder, bvh_wide = resolve_bvh_config()

    def render_one(frame):
        from tpu_render_cluster.render.mesh import scene_mesh_set

        scene = build_scene(scene_name, frame)
        camera = scene_camera(scene_name, frame)
        return render_tile(
            scene,
            camera,
            frame,
            0,
            0,
            width=width,
            height=height,
            tile_height=height,
            tile_width=width,
            samples=samples,
            max_bounces=max_bounces,
            mesh=scene_mesh_set(scene_name, frame, bvh_builder, bvh_wide),
            quant=bvh_quant,
        )

    # shard_map (not jit-level SPMD): the Pallas intersection kernel lowers
    # to a Mosaic custom call the XLA partitioner cannot split, so each
    # device must trace its own per-shard vmap.
    batch_sharding = NamedSharding(mesh, P("d"))
    render_shard = _shard_map(
        jax.vmap(render_one),
        mesh=mesh,
        in_specs=(P("d"),),
        out_specs=P("d", None, None, None),
    )
    return jax.jit(render_shard)(jax.device_put(frames, batch_sharding))
