"""Pass ``loop-blocking``: blocking calls reachable from coroutines.

The asyncio control plane (master/worker/sched/ha) runs dispatch,
heartbeats, and telemetry on ONE event loop; a single ``os.fsync`` on
that loop stalls every worker's heartbeat service (the cost PR 12's
``ha_ledger_append_seconds`` histogram made visible). This pass makes
the "never block the event loop" rule mechanical:

- every ``async def`` in the package is a seed (a superset of the
  master/worker/sched/ha entry points — any coroutine body holds the
  loop while it runs);
- blocking primitives are the ones the ledger/flight-recorder/export
  paths actually use: ``os.fsync``, builtin ``open``, ``time.sleep``,
  ``subprocess.*``, ``json.dump``, and the ``pathlib`` file-IO methods
  (``read_text``/``write_text``/``read_bytes``/``write_bytes``);
- a call routed through ``asyncio.to_thread(...)`` or
  ``run_in_executor(...)`` is a legal hop and is not traversed;
- reachability follows *statically resolvable* sync calls: module-local
  functions, ``from x import y`` targets, ``self.method``, and
  attribute calls whose method name is defined exactly once in the
  package (common container/file method names are never resolved this
  way — see ``_AMBIGUOUS_NAMES``). Dynamically assigned callbacks are
  invisible to the walk, which is why the ledger sinks must be
  non-blocking BY CONSTRUCTION (``ha.ledger.AsyncLedgerAppender``)
  rather than merely unflagged.

Findings anchor at the call site inside the coroutine (the edge where
the event loop enters the blocking path) with the full chain in the
message — that is where a ``to_thread`` hop belongs.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from tpu_render_cluster.lint.core import Finding, LintContext, SourceModule

PASS_ID = "loop-blocking"

# (module alias target, attribute) -> human description.
_BLOCKING_MODULE_CALLS = {
    ("os", "fsync"): "os.fsync()",
    ("time", "sleep"): "time.sleep()",
    ("json", "dump"): "json.dump() to a file object",
}
# Any call into these modules blocks (process spawn + pipe IO).
_BLOCKING_MODULES = {"subprocess"}
# File-IO method names (pathlib and friends) — receiver-independent.
_BLOCKING_METHODS = {"read_text", "write_text", "read_bytes", "write_bytes"}
# Offload seams: a call whose callee is one of these is a legal hop and
# its arguments are not walked (the wrapped callable runs OFF the loop).
_OFFLOAD_ATTRS = {"to_thread", "run_in_executor"}

# Method names too generic to resolve by package-wide uniqueness: lists,
# dicts, files, sockets, futures, and loggers own these. ``self.<name>``
# still resolves (the enclosing class is known).
_AMBIGUOUS_NAMES = {
    "append", "add", "get", "put", "pop", "close", "open", "write", "read",
    "send", "recv", "update", "extend", "remove", "discard", "clear", "set",
    "start", "stop", "run", "join", "cancel", "result", "items", "keys",
    "values", "copy", "encode", "decode", "strip", "split", "format", "info",
    "debug", "warning", "error", "exception", "observe", "inc", "submit",
    "connect", "load", "dump", "dumps", "loads", "wait", "acquire", "release",
}

_MAX_DEPTH = 8


@dataclass
class _Func:
    qualname: str
    module: SourceModule
    node: ast.AST
    is_async: bool
    class_name: str | None
    blocking: list[tuple[int, str]] = field(default_factory=list)
    # (call line, resolution key) — resolved lazily against the index.
    calls: list[tuple[int, "str | tuple[str, str]"]] = field(default_factory=list)


class _BodyScanner(ast.NodeVisitor):
    """Collect blocking primitives + resolvable call edges in ONE function
    body (nested function/class definitions are separate analysis units)."""

    def __init__(self, func: _Func, module_aliases, from_imports):
        self.func = func
        self.module_aliases = module_aliases
        self.from_imports = from_imports
        self._top = True

    def visit_FunctionDef(self, node):  # noqa: N802 - ast API
        if self._top:
            self._top = False
            self.generic_visit(node)
        # nested defs: do not descend

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):  # noqa: N802
        pass

    def visit_ClassDef(self, node):  # noqa: N802
        pass

    def visit_Call(self, node: ast.Call):  # noqa: N802
        callee = node.func
        # Offload hop: asyncio.to_thread(fn, ...) / loop.run_in_executor —
        # nothing inside its argument list runs on the loop.
        if isinstance(callee, ast.Attribute) and callee.attr in _OFFLOAD_ATTRS:
            self.visit(callee.value)
            return
        line = node.lineno
        if isinstance(callee, ast.Name):
            if callee.id == "open":
                self.func.blocking.append((line, "builtin open() file IO"))
            else:
                target = self.from_imports.get(callee.id)
                if target is not None:
                    self.func.calls.append((line, target))
                else:
                    self.func.calls.append((line, ("", callee.id)))
        elif isinstance(callee, ast.Attribute):
            attr = callee.attr
            base = callee.value
            if isinstance(base, ast.Name):
                target_module = self.module_aliases.get(base.id)
                if target_module in _BLOCKING_MODULES:
                    self.func.blocking.append(
                        (line, f"{target_module}.{attr}()")
                    )
                elif (target_module, attr) in _BLOCKING_MODULE_CALLS:
                    self.func.blocking.append(
                        (line, _BLOCKING_MODULE_CALLS[(target_module, attr)])
                    )
                elif target_module is not None:
                    self.func.calls.append((line, (target_module, attr)))
                elif base.id == "self":
                    self.func.calls.append((line, ("self", attr)))
                elif attr in _BLOCKING_METHODS:
                    self.func.blocking.append((line, f".{attr}() file IO"))
                else:
                    self.func.calls.append((line, ("", attr)))
            elif attr in _BLOCKING_METHODS:
                self.func.blocking.append((line, f".{attr}() file IO"))
            else:
                self.func.calls.append((line, ("", attr)))
        self.generic_visit(node)


def _import_maps(module: SourceModule):
    """(module aliases, from-imports) visible at module level."""
    aliases: dict[str, str] = {}
    from_imports: dict[str, tuple[str, str]] = {}
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                aliases[alias.asname or alias.name.split(".")[0]] = alias.name
        elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            for alias in node.names:
                from_imports[alias.asname or alias.name] = (
                    node.module,
                    alias.name,
                )
    return aliases, from_imports


def _collect_functions(ctx: LintContext) -> list[_Func]:
    functions: list[_Func] = []
    for module in ctx.modules:
        aliases, from_imports = _import_maps(module)

        def walk(node, class_name, prefix):
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    qual = f"{prefix}{child.name}"
                    func = _Func(
                        qualname=f"{module.module_name}.{qual}",
                        module=module,
                        node=child,
                        is_async=isinstance(child, ast.AsyncFunctionDef),
                        class_name=class_name,
                    )
                    scanner = _BodyScanner(func, aliases, from_imports)
                    scanner.visit(child)
                    functions.append(func)
                    walk(child, class_name, f"{qual}.<locals>.")
                elif isinstance(child, ast.ClassDef):
                    walk(child, child.name, f"{child.name}.")
                else:
                    walk(child, class_name, prefix)

        walk(module.tree, None, "")
    return functions


class _Index:
    def __init__(self, ctx: LintContext, functions: list[_Func]):
        self.package = ctx.package_root.name
        self.by_name: dict[str, list[_Func]] = {}
        self.by_module_func: dict[tuple[str, str], _Func] = {}
        self.by_class_method: dict[tuple[str, str, str], _Func] = {}
        for func in functions:
            bare = func.qualname.rsplit(".", 1)[-1]
            self.by_name.setdefault(bare, []).append(func)
            if func.class_name is None:
                self.by_module_func[(func.module.module_name, bare)] = func
            else:
                self.by_class_method[
                    (func.module.module_name, func.class_name, bare)
                ] = func

    def resolve(self, caller: _Func, key) -> "_Func | None":
        scope, name = key if isinstance(key, tuple) else ("", key)
        module_name = caller.module.module_name
        if scope == "self" and caller.class_name is not None:
            hit = self.by_class_method.get(
                (module_name, caller.class_name, name)
            )
            if hit is not None:
                return hit
            scope = ""  # fall through to uniqueness
        if scope == "":
            # Bare name: the caller's own module wins before uniqueness.
            hit = self.by_module_func.get((module_name, name))
            if hit is not None:
                return hit
        elif scope != "self":
            # from-import target or module alias: exact module lookup.
            hit = self.by_module_func.get((scope, name))
            if hit is not None:
                return hit
            if not scope.startswith(self.package):
                return None  # stdlib/third-party: not ours to walk
        if name in _AMBIGUOUS_NAMES:
            return None
        candidates = self.by_name.get(name, [])
        return candidates[0] if len(candidates) == 1 else None


def run(ctx: LintContext) -> list[Finding]:
    functions = _collect_functions(ctx)
    index = _Index(ctx, functions)
    findings: list[Finding] = []
    seen: set[tuple[str, int, str, int]] = set()

    def blocking_sites(func: _Func, depth: int, visited: frozenset):
        """Blocking primitives reachable from ``func`` through sync calls:
        yields (site func, site line, description, chain of qualnames)."""
        for line, desc in func.blocking:
            yield func, line, desc, (func.qualname,)
        if depth >= _MAX_DEPTH:
            return
        for line, key in func.calls:
            target = index.resolve(func, key)
            if target is None or target.is_async or id(target) in visited:
                continue
            for site, site_line, desc, chain in blocking_sites(
                target, depth + 1, visited | {id(target)}
            ):
                yield site, site_line, desc, (func.qualname,) + chain

    for seed in functions:
        if not seed.is_async:
            continue
        # Direct blocking in the coroutine body.
        for line, desc in seed.blocking:
            key = (seed.qualname, line, desc, line)
            if key in seen:
                continue
            seen.add(key)
            findings.append(
                Finding(
                    PASS_ID,
                    seed.module.relpath,
                    line,
                    f"coroutine {seed.qualname!r} performs blocking {desc} "
                    "on the event loop — route through asyncio.to_thread "
                    "or an executor",
                )
            )
        # Blocking reached through resolvable sync callees.
        for call_line, call_key in seed.calls:
            target = index.resolve(seed, call_key)
            if target is None or target.is_async:
                continue
            for site, site_line, desc, chain in blocking_sites(
                target, 1, frozenset({id(seed), id(target)})
            ):
                key = (seed.qualname, call_line, desc, site_line)
                if key in seen:
                    continue
                seen.add(key)
                hops = " -> ".join(chain)
                findings.append(
                    Finding(
                        PASS_ID,
                        seed.module.relpath,
                        call_line,
                        f"coroutine {seed.qualname!r} reaches blocking {desc} "
                        f"at {site.module.relpath}:{site_line} without a "
                        "to_thread/executor hop",
                        chain=(f"via {hops}",),
                        sites=((site.module.relpath, site_line),),
                    )
                )
    return findings
