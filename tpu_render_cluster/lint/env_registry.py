"""Pass ``env-registry``: every ``TRC_*`` knob declared once + documented.

The package grew 58 ``TRC_*`` environment knobs across eleven subsystems;
nothing enforced that a knob is declared, documented, or even still read
(the README drifted to 57 rows before this pass existed). The contract,
checked against ``utils/env.py``'s :data:`ENV_VARS` registry:

1. ``os.environ`` / ``os.getenv`` access with a ``TRC_*`` name happens
   ONLY inside ``utils/env.py`` — everywhere else reads go through the
   ``env_int``/``env_float``/``env_str`` helpers (call-time semantics,
   logged fallbacks, and a single choke point this pass can see).
2. Every name passed to a helper (as a literal) is declared in the
   registry; dynamic names (``resolve_telemetry_port(env_name)``) are
   exempt — their literals still hit check 3 at the call site's module.
3. Every declared name is mentioned somewhere in package code (a
   declaration nothing reads is dead and must be deleted) and appears in
   a README environment-table row; every ``TRC_*`` token in a README
   table row is declared (a documented knob that does not exist is worse
   than an undocumented one).
4. ``utils/env.py`` declares each name exactly once.
"""

from __future__ import annotations

import ast
import re

from tpu_render_cluster.lint.core import Finding, LintContext

PASS_ID = "env-registry"

_ENV_HELPERS = {"env_int", "env_float", "env_str"}
_TRC = re.compile(r"TRC_[A-Z0-9_]*[A-Z0-9]")


def _docstring_nodes(tree: ast.AST) -> set[int]:
    """ids of Constant nodes that are module/class/function docstrings."""
    out: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(
            node, (ast.Module, ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            body = getattr(node, "body", [])
            if (
                body
                and isinstance(body[0], ast.Expr)
                and isinstance(body[0].value, ast.Constant)
                and isinstance(body[0].value.value, str)
            ):
                out.add(id(body[0].value))
    return out


def _is_environ_access(node: ast.expr) -> bool:
    """``os.environ`` attribute or ``os.getenv`` callee."""
    return (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "os"
        and node.attr in ("environ", "getenv")
    )


def run(ctx: LintContext) -> list[Finding]:
    if ctx.env_registry is not None:
        registry = dict(ctx.env_registry)
    else:
        from tpu_render_cluster.utils.env import ENV_VARS

        registry = dict(ENV_VARS)

    findings: list[Finding] = []
    env_module = ctx.module_by_suffix(ctx.env_module_suffix)
    mentioned: set[str] = set()
    declare_lines: dict[str, int] = {}

    for module in ctx.modules:
        is_env_module = module is env_module
        docstrings = _docstring_nodes(module.tree)
        for node in ast.walk(module.tree):
            # Non-docstring TRC_ literals count as "read/mentioned" —
            # except inside utils/env.py itself, where the declare()
            # literal must not count as its own reader.
            if (
                not is_env_module
                and isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and id(node) not in docstrings
            ):
                mentioned.update(_TRC.findall(node.value))
            # Direct os.environ/getenv reads of TRC_ names.
            if not is_env_module:
                trc_name = None
                if isinstance(node, ast.Subscript) and _is_environ_access(
                    node.value
                ):
                    if isinstance(node.slice, ast.Constant) and isinstance(
                        node.slice.value, str
                    ):
                        trc_name = node.slice.value
                elif isinstance(node, ast.Call):
                    callee = node.func
                    if _is_environ_access(callee) or (
                        isinstance(callee, ast.Attribute)
                        and callee.attr in ("get", "setdefault")
                        and _is_environ_access(callee.value)
                    ):
                        # os.getenv("X") / os.environ.get("X")
                        if (
                            node.args
                            and isinstance(node.args[0], ast.Constant)
                            and isinstance(node.args[0].value, str)
                        ):
                            trc_name = node.args[0].value
                if trc_name is not None and trc_name.startswith("TRC_"):
                    findings.append(
                        Finding(
                            PASS_ID,
                            module.relpath,
                            node.lineno,
                            f"direct os.environ read of {trc_name} — route "
                            "through tpu_render_cluster.utils.env "
                            "(env_int/env_float/env_str) so the knob is "
                            "declared, documented, and read at call time",
                        )
                    )
            # Helper reads: literal first arg must be declared.
            if isinstance(node, ast.Call):
                callee_name = None
                if isinstance(node.func, ast.Name):
                    callee_name = node.func.id
                elif isinstance(node.func, ast.Attribute):
                    callee_name = node.func.attr
                if (
                    callee_name in _ENV_HELPERS
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and node.args[0].value.startswith("TRC_")
                    and node.args[0].value not in registry
                    and not is_env_module
                ):
                    findings.append(
                        Finding(
                            PASS_ID,
                            module.relpath,
                            node.lineno,
                            f"read of undeclared {node.args[0].value} — "
                            "declare() it in utils/env.py (and document it "
                            "in README's environment table)",
                        )
                    )

    # Declaration sites (line anchors + exactly-once check).
    if env_module is not None:
        for node in ast.walk(env_module.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "declare"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                name = node.args[0].value
                if name in declare_lines:
                    findings.append(
                        Finding(
                            PASS_ID,
                            env_module.relpath,
                            node.lineno,
                            f"{name} declared more than once (first at line "
                            f"{declare_lines[name]})",
                        )
                    )
                else:
                    declare_lines[name] = node.lineno

    # README environment-table cross-check.
    documented: dict[str, int] = {}
    for lineno, line in enumerate(ctx.readme().splitlines(), start=1):
        if line.lstrip().startswith("|"):
            for name in _TRC.findall(line):
                documented.setdefault(name, lineno)

    env_relpath = env_module.relpath if env_module is not None else "utils/env.py"
    for name in sorted(registry):
        anchor = declare_lines.get(name, 1)
        if name not in mentioned:
            findings.append(
                Finding(
                    PASS_ID,
                    env_relpath,
                    anchor,
                    f"{name} is declared but nothing in the package reads "
                    "it — delete the dead declaration (and its README row)",
                )
            )
        if name not in documented:
            findings.append(
                Finding(
                    PASS_ID,
                    env_relpath,
                    anchor,
                    f"{name} is declared but missing from README's "
                    "environment tables — add a row",
                )
            )
    for name, lineno in sorted(documented.items()):
        if name not in registry:
            findings.append(
                Finding(
                    PASS_ID,
                    "README.md",
                    lineno,
                    f"README documents {name} but utils/env.py does not "
                    "declare it — stale row or missing declare()",
                )
            )
    return findings
