"""Pass ``jit-purity``: host side effects inside traced functions.

A ``jax.jit``/``pallas_call``/``shard_map``-traced function body runs at
TRACE time, once per compiled shape — a ``time.time()`` or ``np.random``
call inside it silently bakes one host value into the executable (the
classic "why does my render never change" bug), an env read makes the
compiled program diverge from the environment after the first trace, and
metric registration from inside a trace registers once per COMPILE, not
per execution. This pass finds the traced functions statically:

- defs decorated with ``jit``/``jax.jit``/``pjit``/``shard_map`` (bare or
  under ``functools.partial``);
- defs passed by name to ``jit(...)``, ``pallas_call(...)``,
  ``shard_map(...)`` anywhere in the package (first positional or any
  arg);
- defs RETURNED by a factory whose call result is passed to one of those
  wrappers (``jax.jit(make_renderer(...))`` — the dominant idiom in
  ``render/``: the factory body is host code, the returned closure is
  traced).

Inside a traced body (nested defs included — they trace too) it flags:
``time.*``, ``np.random``/``random``/``secrets``/``datetime.now``,
``os.environ``/``os.getenv``/``env_int``/``env_float``/``env_str``,
``print``/``open``/``input``, metric registration/mutation
(``.counter``/``.gauge``/``.histogram``/``.observe``/``.inc``), and
``global`` statements. ``jax.debug.print`` and the rest of the jax/jnp
surface are pure by contract and not flagged.
"""

from __future__ import annotations

import ast

from tpu_render_cluster.lint.core import Finding, LintContext, SourceModule

PASS_ID = "jit-purity"

_TRACE_WRAPPER_NAMES = {"jit", "pjit", "pallas_call", "shard_map"}
_ENV_HELPERS = {"env_int", "env_float", "env_str"}
_METRIC_METHODS = {"counter", "gauge", "histogram", "observe", "inc"}
_IMPURE_MODULES = {"time", "random", "secrets"}
_IMPURE_BUILTINS = {"print", "open", "input"}


def _callable_name(node: ast.expr) -> str | None:
    """Final name of a call target: ``jax.jit`` -> ``jit``."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _unwrap_partial(node: ast.expr) -> ast.expr:
    """``functools.partial(jax.jit, ...)`` decorators / wrappers."""
    if (
        isinstance(node, ast.Call)
        and _callable_name(node.func) == "partial"
        and node.args
    ):
        return node.args[0]
    return node


def _is_trace_wrapper(node: ast.expr) -> bool:
    return _callable_name(_unwrap_partial(node)) in _TRACE_WRAPPER_NAMES


class _ModuleDefs(ast.NodeVisitor):
    """Index every def in a module by bare name (innermost duplicates
    shadow is fine — names are module-unique in practice)."""

    def __init__(self) -> None:
        self.defs: dict[str, ast.AST] = {}

    def visit_FunctionDef(self, node):  # noqa: N802
        self.defs.setdefault(node.name, node)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef


def _returned_defs(factory: ast.AST) -> list[ast.AST]:
    """Inner defs a factory returns (directly or via a local name)."""
    inner: dict[str, ast.AST] = {}
    for child in ast.walk(factory):
        if isinstance(child, ast.FunctionDef) and child is not factory:
            inner[child.name] = child
    out = []
    for child in ast.walk(factory):
        if isinstance(child, ast.Return) and isinstance(child.value, ast.Name):
            if child.value.id in inner:
                out.append(inner[child.value.id])
    return out


def _traced_defs(module: SourceModule, package_defs: dict[str, list[ast.AST]]):
    """AST nodes of this module's traced functions (and which modules the
    cross-module factory resolution touched)."""
    defs = _ModuleDefs()
    defs.visit(module.tree)
    traced: list[ast.AST] = []

    # Decorated defs.
    for node in ast.walk(module.tree):
        if isinstance(node, ast.FunctionDef):
            for decorator in node.decorator_list:
                target = _unwrap_partial(decorator)
                if isinstance(target, ast.Call):
                    target = target.func
                if _callable_name(target) in _TRACE_WRAPPER_NAMES:
                    traced.append(node)
                    break

    # Wrapper call sites: jit(f), pallas_call(kernel, ...), shard_map(f,...).
    for node in ast.walk(module.tree):
        if not (isinstance(node, ast.Call) and _is_trace_wrapper(node.func)):
            continue
        candidates = list(node.args) + [kw.value for kw in node.keywords]
        for arg in candidates:
            arg = _unwrap_partial(arg)
            if isinstance(arg, ast.Name):
                hit = defs.defs.get(arg.id)
                if hit is not None:
                    traced.append(hit)
            elif isinstance(arg, ast.Call):
                factory_name = _callable_name(arg.func)
                if factory_name is None:
                    continue
                factory = defs.defs.get(factory_name)
                if factory is not None:
                    traced.extend(_returned_defs(factory))
                else:
                    # Cross-module factory: resolve by package-unique name.
                    matches = package_defs.get(factory_name, [])
                    if len(matches) == 1:
                        traced.extend(_returned_defs(matches[0]))
    return traced


class _ImpurityScanner(ast.NodeVisitor):
    """Flag host effects anywhere inside one traced def (nested included)."""

    def __init__(self, module: SourceModule, qualname: str):
        self.module = module
        self.qualname = qualname
        self.findings: list[Finding] = []

    def _flag(self, node: ast.AST, what: str) -> None:
        self.findings.append(
            Finding(
                PASS_ID,
                self.module.relpath,
                node.lineno,
                f"traced function {self.qualname!r} {what} — host effects "
                "run once per trace, not per execution; hoist to the "
                "factory/caller or thread the value in as an argument",
            )
        )

    def visit_Global(self, node):  # noqa: N802
        self._flag(node, "mutates module globals (`global` statement)")

    def visit_Attribute(self, node: ast.Attribute):  # noqa: N802
        base = node.value
        if isinstance(base, ast.Name):
            if base.id == "os" and node.attr in ("environ", "getenv"):
                self._flag(node, "reads os.environ at trace time")
            elif (
                base.id in ("np", "numpy") and node.attr == "random"
            ):
                self._flag(node, "uses host numpy RNG (np.random)")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):  # noqa: N802
        callee = node.func
        if isinstance(callee, ast.Name):
            if callee.id in _IMPURE_BUILTINS:
                self._flag(node, f"calls {callee.id}()")
            elif callee.id in _ENV_HELPERS:
                self._flag(node, f"reads the environment via {callee.id}()")
        elif isinstance(callee, ast.Attribute):
            base = callee.value
            if isinstance(base, ast.Name) and base.id in _IMPURE_MODULES:
                self._flag(node, f"calls {base.id}.{callee.attr}()")
            elif isinstance(base, ast.Name) and base.id == "datetime":
                self._flag(node, f"calls datetime.{callee.attr}()")
            elif callee.attr in _METRIC_METHODS and node.args:
                first = node.args[0]
                if isinstance(first, ast.Constant) and isinstance(
                    first.value, str
                ):
                    self._flag(
                        node, f"registers/mutates a metric (.{callee.attr}())"
                    )
        self.generic_visit(node)


def run(ctx: LintContext) -> list[Finding]:
    # Package-wide def index for cross-module factory resolution.
    package_defs: dict[str, list[ast.AST]] = {}
    def_module: dict[int, SourceModule] = {}
    for module in ctx.modules:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.FunctionDef):
                package_defs.setdefault(node.name, []).append(node)
                def_module[id(node)] = module

    findings: list[Finding] = []
    seen: set[int] = set()
    for module in ctx.modules:
        for node in _traced_defs(module, package_defs):
            if id(node) in seen:
                continue
            seen.add(id(node))
            owner = def_module.get(id(node), module)
            scanner = _ImpurityScanner(owner, node.name)
            for child in ast.iter_child_nodes(node):
                scanner.visit(child)
            findings.extend(scanner.findings)
    return findings
