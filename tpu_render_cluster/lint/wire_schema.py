"""Pass ``wire-schema``: the optional-key idiom, machine-checked.

The protocol's reference compatibility rests on one rule, held by
convention since PR 3: every beyond-reference extension rides as an
OPTIONAL payload key that is **omitted when absent** — never serialized
as ``null`` or a default — so single-job / untiled / ledger-less traffic
stays byte-identical to the reference and C++ peers route unmodified.

This pass checks the three artifacts that must agree:

- ``protocol/schema.py`` (:data:`WIRE_SCHEMAS`) — the declared contract:
  required vs optional keys per wire tag;
- ``protocol/messages.py`` — every message class's construct/parse site:
  ``to_payload`` must assign required keys unconditionally and optional
  keys only under a presence guard, and must not invent undeclared keys;
  ``from_payload`` must not demand an optional key's presence (subscript
  read) and must not read undeclared keys; tags must map 1:1 to schemas;
- PROTOCOL.md — the message table must list exactly the declared tags,
  and each optional key must be mentioned (backticked) in its tag's row,
  so the human contract can no longer silently trail the code.
"""

from __future__ import annotations

import ast
import re

from tpu_render_cluster.lint.core import Finding, LintContext, SourceModule

PASS_ID = "wire-schema"

_ROW_RE = re.compile(r"^\|\s*`(?P<tag>[^`]+)`\s*\|")


def _helper_key_map(module: SourceModule) -> dict[str, set[str]]:
    """Module-level ``_x_from_payload(payload)`` helpers -> the payload
    keys their bodies read (``payload.get("k")`` / ``payload["k"]``)."""
    helpers: dict[str, set[str]] = {}
    for node in module.tree.body:
        if not isinstance(node, ast.FunctionDef):
            continue
        params = {a.arg for a in node.args.args}
        if "payload" not in params:
            continue
        keys = _payload_reads(node, "payload")
        if keys["strict"] or keys["lenient"]:
            helpers[node.name] = keys["strict"] | keys["lenient"]
    return helpers


def _payload_reads(node: ast.AST, param: str) -> dict[str, set[str]]:
    """Keys read off ``param`` inside ``node``: subscript (strict,
    presence-demanding) vs ``.get`` (lenient)."""
    strict: set[str] = set()
    lenient: set[str] = set()
    for child in ast.walk(node):
        if (
            isinstance(child, ast.Subscript)
            and isinstance(child.value, ast.Name)
            and child.value.id == param
            and isinstance(child.slice, ast.Constant)
            and isinstance(child.slice.value, str)
        ):
            strict.add(child.slice.value)
        elif (
            isinstance(child, ast.Call)
            and isinstance(child.func, ast.Attribute)
            and child.func.attr == "get"
            and isinstance(child.func.value, ast.Name)
            and child.func.value.id == param
            and child.args
            and isinstance(child.args[0], ast.Constant)
            and isinstance(child.args[0].value, str)
        ):
            lenient.add(child.args[0].value)
    return {"strict": strict, "lenient": lenient}


def _dict_literal_keys(node: ast.expr) -> set[str]:
    if not isinstance(node, ast.Dict):
        return set()
    return {
        k.value
        for k in node.keys
        if isinstance(k, ast.Constant) and isinstance(k.value, str)
    }


def _to_payload_keys(func: ast.FunctionDef) -> tuple[dict[str, int], dict[str, int]]:
    """(unconditional keys, conditional keys) -> first line, from one
    ``to_payload`` body. Unconditional = assigned at statement level
    (initial dict literal, returned literal, or ``out["k"] = ...``);
    conditional = the same inside any ``if``."""
    unconditional: dict[str, int] = {}
    conditional: dict[str, int] = {}

    def record(keys: set[str], line: int, in_if: bool) -> None:
        target = conditional if in_if else unconditional
        for key in keys:
            target.setdefault(key, line)

    def walk(statements, in_if: bool) -> None:
        for stmt in statements:
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                value = stmt.value
                targets = (
                    stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
                )
                if value is not None:
                    record(_dict_literal_keys(value), stmt.lineno, in_if)
                for target in targets:
                    if (
                        isinstance(target, ast.Subscript)
                        and isinstance(target.slice, ast.Constant)
                        and isinstance(target.slice.value, str)
                    ):
                        record({target.slice.value}, stmt.lineno, in_if)
            elif isinstance(stmt, ast.Return) and stmt.value is not None:
                record(_dict_literal_keys(stmt.value), stmt.lineno, in_if)
            elif isinstance(stmt, ast.If):
                walk(stmt.body, True)
                walk(stmt.orelse, True)
            elif isinstance(stmt, (ast.With, ast.For, ast.While, ast.Try)):
                for block in (
                    getattr(stmt, "body", []),
                    getattr(stmt, "orelse", []),
                    getattr(stmt, "finalbody", []),
                ):
                    walk(block, True)

    walk(func.body, False)
    return unconditional, conditional


def _message_classes(module: SourceModule):
    """(class node, wire tag) for every class declaring ``type_name``."""
    for node in module.tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        tag = None
        for stmt in node.body:
            value = None
            name = None
            if isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name
            ):
                name, value = stmt.target.id, stmt.value
            elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and isinstance(
                stmt.targets[0], ast.Name
            ):
                name, value = stmt.targets[0].id, stmt.value
            if (
                name == "type_name"
                and isinstance(value, ast.Constant)
                and isinstance(value.value, str)
            ):
                tag = value.value
        if tag is not None:
            yield node, tag


def _check_frame_segments(
    ctx: LintContext, registry: dict, segments: dict
) -> list[Finding]:
    """Preserialized-frame contract (PR 17): every declared segment split
    must exactly partition its tag's payload keys, the splice codec must
    cover every key it is responsible for, and PROTOCOL.md must carry the
    split's documentation (the no-bytes-added guarantee)."""
    findings: list[Finding] = []
    schema_path = "protocol/schema.py"
    for tag, seg in sorted(segments.items()):
        schema = registry.get(tag)
        if schema is None:
            findings.append(
                Finding(
                    PASS_ID,
                    schema_path,
                    1,
                    f"FRAME_SEGMENTS declares segments for {tag!r}, which "
                    "no wire schema declares",
                )
            )
            continue
        constant, varying = set(seg.constant), set(seg.varying)
        overlap = constant & varying
        if overlap:
            findings.append(
                Finding(
                    PASS_ID,
                    schema_path,
                    1,
                    f"{tag}: segment keys {sorted(overlap)} are declared "
                    "both constant and varying",
                )
            )
        declared = set(schema.required) | set(schema.optional)
        if constant | varying != declared:
            missing = sorted(declared - constant - varying)
            extra = sorted((constant | varying) - declared)
            findings.append(
                Finding(
                    PASS_ID,
                    schema_path,
                    1,
                    f"{tag}: segment split must exactly partition the "
                    f"declared payload keys (missing {missing}, "
                    f"undeclared {extra})",
                )
            )
        # The splice codec must mention every key as a JSON splice point:
        # a key it cannot emit would silently vanish from the wire.
        frames_module = ctx.module_by_suffix("protocol.frames")
        if frames_module is not None:
            literals = "".join(
                node.value
                for node in ast.walk(frames_module.tree)
                if isinstance(node, ast.Constant)
                and isinstance(node.value, str)
            )
            for key in sorted((constant | varying) & declared):
                if f'"{key}":' not in literals:
                    findings.append(
                        Finding(
                            PASS_ID,
                            frames_module.relpath,
                            1,
                            f"{tag}: segment key {key!r} has no splice "
                            "point in the frame codec",
                        )
                    )
        # PROTOCOL.md: the split is a documented contract, like the
        # optional-key rows in the message table.
        doc = ctx.protocol_md()
        if doc and "Preserialized dispatch frames" not in doc:
            findings.append(
                Finding(
                    PASS_ID,
                    "PROTOCOL.md",
                    1,
                    f"{tag}: declares a preserialized segment split but "
                    'PROTOCOL.md has no "Preserialized dispatch frames" '
                    "section",
                )
            )
        elif doc:
            section = doc.split("Preserialized dispatch frames", 1)[1]
            for key in sorted(constant):
                if f"`{key}`" not in section:
                    findings.append(
                        Finding(
                            PASS_ID,
                            "PROTOCOL.md",
                            1,
                            f"{tag}: constant segment key `{key}` is not "
                            'mentioned in the "Preserialized dispatch '
                            'frames" section',
                        )
                    )
    return findings


def run(ctx: LintContext) -> list[Finding]:
    if ctx.wire_registry is not None:
        registry = dict(ctx.wire_registry)
        # Fixture mode: segment checks only run when the fixture supplies
        # segments too (tests exercising the classic key checks must not
        # trip on the real package's segment registry).
        segments = dict(ctx.frame_segments or {})
    else:
        from tpu_render_cluster.protocol.schema import (
            FRAME_SEGMENTS,
            WIRE_SCHEMAS,
        )

        registry = dict(WIRE_SCHEMAS)
        segments = (
            dict(ctx.frame_segments)
            if ctx.frame_segments is not None
            else dict(FRAME_SEGMENTS)
        )

    findings: list[Finding] = []
    findings.extend(_check_frame_segments(ctx, registry, segments))
    module = ctx.module_by_suffix(ctx.messages_module_suffix)
    if module is None:
        return [
            Finding(
                PASS_ID,
                str(ctx.package_root),
                1,
                f"no module matching *.{ctx.messages_module_suffix} found",
            )
        ]
    helpers = _helper_key_map(module)
    seen_tags: set[str] = set()

    for node, tag in _message_classes(module):
        schema = registry.get(tag)
        if schema is None:
            findings.append(
                Finding(
                    PASS_ID,
                    module.relpath,
                    node.lineno,
                    f"message class {node.name} declares wire tag {tag!r} "
                    "with no schema in protocol/schema.py",
                )
            )
            continue
        seen_tags.add(tag)
        required = set(schema.required)
        optional = set(schema.optional)
        to_payload = next(
            (
                s
                for s in node.body
                if isinstance(s, ast.FunctionDef) and s.name == "to_payload"
            ),
            None,
        )
        from_payload = next(
            (
                s
                for s in node.body
                if isinstance(s, ast.FunctionDef) and s.name == "from_payload"
            ),
            None,
        )
        if to_payload is not None:
            unconditional, conditional = _to_payload_keys(to_payload)
            assigned = set(unconditional) | set(conditional)
            for key in sorted(required - set(unconditional)):
                line = conditional.get(key, to_payload.lineno)
                findings.append(
                    Finding(
                        PASS_ID,
                        module.relpath,
                        line,
                        f"{tag}: required key {key!r} is "
                        + (
                            "only conditionally serialized"
                            if key in conditional
                            else "never serialized"
                        )
                        + " by to_payload",
                    )
                )
            for key in sorted(optional & set(unconditional)):
                findings.append(
                    Finding(
                        PASS_ID,
                        module.relpath,
                        unconditional[key],
                        f"{tag}: optional key {key!r} is serialized "
                        "unconditionally — the optional-key idiom requires "
                        "omitted-when-absent (guard on presence; never "
                        "write null/defaults)",
                    )
                )
            for key in sorted(assigned - required - optional):
                findings.append(
                    Finding(
                        PASS_ID,
                        module.relpath,
                        unconditional.get(key) or conditional.get(key, 1),
                        f"{tag}: to_payload writes undeclared key {key!r} — "
                        "declare it in protocol/schema.py (and PROTOCOL.md)",
                    )
                )
        if from_payload is not None:
            reads = _payload_reads(from_payload, "payload")
            # Expand helper calls: _epoch_from_payload(payload) etc.
            for child in ast.walk(from_payload):
                if (
                    isinstance(child, ast.Call)
                    and isinstance(child.func, ast.Name)
                    and child.func.id in helpers
                    and any(
                        isinstance(a, ast.Name) and a.id == "payload"
                        for a in child.args
                    )
                ):
                    reads["lenient"] |= helpers[child.func.id]
            for key in sorted(reads["strict"] & optional):
                findings.append(
                    Finding(
                        PASS_ID,
                        module.relpath,
                        from_payload.lineno,
                        f"{tag}: optional key {key!r} is read with a "
                        "presence-demanding subscript — use .get()/a "
                        "helper so reference-shaped frames still parse",
                    )
                )
            for key in sorted(
                (reads["strict"] | reads["lenient"]) - required - optional
            ):
                findings.append(
                    Finding(
                        PASS_ID,
                        module.relpath,
                        from_payload.lineno,
                        f"{tag}: from_payload reads undeclared key {key!r}",
                    )
                )

    for tag in sorted(set(registry) - seen_tags):
        findings.append(
            Finding(
                PASS_ID,
                module.relpath,
                1,
                f"schema declares wire tag {tag!r} but protocol/messages.py "
                "defines no class for it",
            )
        )

    # -- PROTOCOL.md message table ------------------------------------------
    doc_rows: dict[str, tuple[int, str]] = {}
    in_table = False
    for lineno, line in enumerate(ctx.protocol_md().splitlines(), start=1):
        if "| Wire tag |" in line:
            in_table = True
            continue
        if in_table:
            if not line.lstrip().startswith("|"):
                in_table = False
                continue
            match = _ROW_RE.match(line.strip())
            if match and not set(match.group("tag")) <= {"-"}:
                doc_rows[match.group("tag")] = (lineno, line)
    if doc_rows:
        for tag in sorted(set(registry) - set(doc_rows)):
            findings.append(
                Finding(
                    PASS_ID,
                    "PROTOCOL.md",
                    1,
                    f"message table is missing a row for {tag!r}",
                )
            )
        for tag, (lineno, row) in sorted(doc_rows.items()):
            schema = registry.get(tag)
            if schema is None:
                findings.append(
                    Finding(
                        PASS_ID,
                        "PROTOCOL.md",
                        lineno,
                        f"message table lists {tag!r}, which no schema "
                        "declares",
                    )
                )
                continue
            for key in schema.optional:
                if f"`{key}`" not in row:
                    findings.append(
                        Finding(
                            PASS_ID,
                            "PROTOCOL.md",
                            lineno,
                            f"{tag}: optional key `{key}` is not mentioned "
                            "in the tag's message-table row",
                        )
                    )
    return findings
