"""trc-lint core: module walker, finding model, pragma grammar, pass runner.

The codebase-native static-analysis layer (ARCHITECTURE §L12). Passes are
plain functions ``run(ctx) -> list[Finding]`` registered in
:data:`tpu_render_cluster.lint.PASSES`; this module owns everything they
share — source discovery, the finding model, and the suppression pragma:

    # trc-lint: disable=<pass>[,<pass>] (<reason>)

A pragma suppresses findings of the named pass(es) on its own line, or on
the line directly below when the pragma stands alone on its line; a
call-chain finding is additionally suppressible at the blocking site it
reports (``Finding.sites``), so one explained pragma covers every
coroutine that reaches that site. The
pragma grammar is itself linted (the ``pragma`` meta-pass): a suppression
without a parenthesized reason, naming an unknown pass, or suppressing
nothing is a finding — "the suite ships green" therefore also means
"every suppression is explained and load-bearing".
"""

from __future__ import annotations

import ast
import io
import json
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable

PRAGMA_PASS_ID = "pragma"

_PRAGMA_RE = re.compile(
    r"trc-lint:\s*disable=(?P<passes>[A-Za-z0-9_-]+(?:\s*,\s*[A-Za-z0-9_-]+)*)"
    r"(?P<rest>.*)$"
)
# Greedy to the LAST ')': reasons may themselves contain parentheses.
_REASON_RE = re.compile(r"^\s*\((?P<reason>.+)\)\s*$")


@dataclass(frozen=True)
class Pragma:
    """One ``trc-lint: disable=`` comment."""

    line: int
    passes: tuple[str, ...]
    reason: str | None
    standalone: bool  # comment-only line: also covers the next line

    @property
    def covered_lines(self) -> tuple[int, ...]:
        return (self.line, self.line + 1) if self.standalone else (self.line,)


@dataclass(frozen=True)
class Finding:
    """One defect: pass id, location, message, optional call chain."""

    pass_id: str
    path: str  # repo-relative where possible
    line: int
    message: str
    severity: str = "error"
    chain: tuple[str, ...] = ()
    # Additional (path, line) anchors along a call chain: a pragma at ANY
    # of them suppresses the finding, so one explained suppression at the
    # blocking site covers every coroutine that reaches it.
    sites: tuple[tuple[str, int], ...] = ()

    def format(self) -> str:
        text = f"{self.path}:{self.line}: [{self.pass_id}] {self.message}"
        for hop in self.chain:
            text += f"\n    {hop}"
        return text

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "pass": self.pass_id,
            "path": self.path,
            "line": self.line,
            "severity": self.severity,
            "message": self.message,
        }
        if self.chain:
            out["chain"] = list(self.chain)
        if self.sites:
            out["sites"] = [list(site) for site in self.sites]
        return out


class SourceModule:
    """One parsed source file: AST + pragma table + dotted module name."""

    def __init__(self, path: Path, text: str, module_name: str, relpath: str):
        self.path = path
        self.text = text
        self.module_name = module_name
        self.relpath = relpath
        self.tree = ast.parse(text, filename=str(path))
        self.pragmas: list[Pragma] = _parse_pragmas(text)

    @classmethod
    def load(cls, path: Path, package_root: Path) -> "SourceModule":
        text = path.read_text(encoding="utf-8")
        rel = path.relative_to(package_root.parent)
        module_name = ".".join(rel.with_suffix("").parts)
        if module_name.endswith(".__init__"):
            module_name = module_name[: -len(".__init__")]
        return cls(path, text, module_name, str(rel))

    def pragmas_covering(self, line: int) -> list[Pragma]:
        return [p for p in self.pragmas if line in p.covered_lines]


def _parse_pragmas(text: str) -> list[Pragma]:
    """Extract pragma comments via the tokenizer (never fooled by ``#``
    inside string literals)."""
    pragmas: list[Pragma] = []
    try:
        tokens = tokenize.generate_tokens(io.StringIO(text).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            match = _PRAGMA_RE.search(tok.string)
            if match is None:
                continue
            passes = tuple(
                p.strip() for p in match.group("passes").split(",") if p.strip()
            )
            reason_match = _REASON_RE.match(match.group("rest") or "")
            reason = reason_match.group("reason").strip() if reason_match else None
            standalone = tok.line[: tok.start[1]].strip() == ""
            pragmas.append(
                Pragma(tok.start[0], passes, reason or None, standalone)
            )
    except (tokenize.TokenError, IndentationError):  # pragma: no cover
        pass
    return pragmas


def discover_modules(package_root: Path) -> list[SourceModule]:
    modules = []
    for path in sorted(package_root.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        modules.append(SourceModule.load(path, package_root))
    return modules


@dataclass
class LintContext:
    """Everything the passes need: the parsed package plus the documents
    and registries the codebase-native checks bind to. Tests point the
    registry/document fields at fixtures; the CLI uses the real ones."""

    package_root: Path
    repo_root: Path
    modules: list[SourceModule] = field(default_factory=list)
    # Overrides for tests (None -> the real registry / document).
    env_registry: dict[str, Any] | None = None
    wire_registry: dict[str, Any] | None = None
    frame_segments: dict[str, Any] | None = None
    readme_text: str | None = None
    protocol_text: str | None = None
    # Dotted-name suffixes locating the codebase-native anchor modules.
    env_module_suffix: str = "utils.env"
    messages_module_suffix: str = "protocol.messages"

    @classmethod
    def for_package(
        cls,
        package_root: Path | None = None,
        repo_root: Path | None = None,
        **overrides: Any,
    ) -> "LintContext":
        if package_root is None:
            package_root = Path(__file__).resolve().parents[1]
        package_root = Path(package_root)
        if repo_root is None:
            repo_root = package_root.parent
        ctx = cls(package_root=package_root, repo_root=Path(repo_root), **overrides)
        ctx.modules = discover_modules(package_root)
        return ctx

    # -- document access -----------------------------------------------------

    def readme(self) -> str:
        if self.readme_text is not None:
            return self.readme_text
        path = self.repo_root / "README.md"
        return path.read_text(encoding="utf-8") if path.is_file() else ""

    def protocol_md(self) -> str:
        if self.protocol_text is not None:
            return self.protocol_text
        path = self.repo_root / "PROTOCOL.md"
        return path.read_text(encoding="utf-8") if path.is_file() else ""

    def module_by_suffix(self, suffix: str) -> SourceModule | None:
        for module in self.modules:
            if module.module_name == suffix or module.module_name.endswith(
                "." + suffix
            ):
                return module
        return None

    def display_path(self, path: Path | str) -> str:
        path = Path(path)
        try:
            return str(path.relative_to(self.repo_root))
        except ValueError:
            return str(path)


@dataclass
class LintReport:
    findings: list[Finding]
    passes_run: tuple[str, ...]
    files_scanned: int
    suppressions_used: int

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_dict(self) -> dict[str, Any]:
        counts: dict[str, int] = {}
        for finding in self.findings:
            counts[finding.pass_id] = counts.get(finding.pass_id, 0) + 1
        return {
            "ok": self.ok,
            "passes": list(self.passes_run),
            "files_scanned": self.files_scanned,
            "suppressions_used": self.suppressions_used,
            "counts": counts,
            "findings": [f.to_dict() for f in self.findings],
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    def format(self) -> str:
        if self.ok:
            return (
                f"trc-lint: clean — {self.files_scanned} file(s), "
                f"{len(self.passes_run)} pass(es), "
                f"{self.suppressions_used} explained suppression(s)."
            )
        lines = [f.format() for f in self.findings]
        lines.append(
            f"trc-lint: {len(self.findings)} finding(s) across "
            f"{self.files_scanned} file(s)."
        )
        return "\n".join(lines)


PassFn = Callable[[LintContext], list[Finding]]


def run_lint(
    ctx: LintContext,
    passes: dict[str, PassFn],
    pass_ids: tuple[str, ...] | None = None,
) -> LintReport:
    """Run the selected passes, apply suppression pragmas, and lint the
    pragmas themselves (reason required; unknown pass refused; a pragma
    that suppresses nothing is dead weight and flagged — but only when
    every pass it names actually ran, so partial runs stay quiet)."""
    selected = tuple(pass_ids) if pass_ids is not None else tuple(passes)
    unknown = [p for p in selected if p not in passes]
    if unknown:
        raise ValueError(f"unknown pass(es): {', '.join(unknown)}")
    raw: list[Finding] = []
    for pass_id in selected:
        raw.extend(passes[pass_id](ctx))

    module_by_relpath = {m.relpath: m for m in ctx.modules}
    used: set[tuple[str, int]] = set()  # (relpath, pragma line)
    kept: list[Finding] = []
    for finding in raw:
        suppressing: list[tuple[str, int]] = []
        for path, line in ((finding.path, finding.line), *finding.sites):
            module = module_by_relpath.get(path)
            if module is None:
                continue
            for pragma in module.pragmas_covering(line):
                if finding.pass_id in pragma.passes:
                    suppressing.append((module.relpath, pragma.line))
        if suppressing:
            used.update(suppressing)
        else:
            kept.append(finding)

    known_ids = set(passes) | {PRAGMA_PASS_ID}
    for module in ctx.modules:
        for pragma in module.pragmas:
            if pragma.reason is None:
                kept.append(
                    Finding(
                        PRAGMA_PASS_ID,
                        module.relpath,
                        pragma.line,
                        "suppression pragma without a reason — write "
                        "`# trc-lint: disable=<pass> (<why this is safe>)`",
                    )
                )
            bad = [p for p in pragma.passes if p not in known_ids]
            if bad:
                kept.append(
                    Finding(
                        PRAGMA_PASS_ID,
                        module.relpath,
                        pragma.line,
                        f"suppression names unknown pass(es): {', '.join(bad)}",
                    )
                )
            elif (
                (module.relpath, pragma.line) not in used
                and all(p in selected for p in pragma.passes)
            ):
                kept.append(
                    Finding(
                        PRAGMA_PASS_ID,
                        module.relpath,
                        pragma.line,
                        "suppression suppresses nothing — remove it (the "
                        "finding it once silenced is gone)",
                    )
                )

    kept.sort(key=lambda f: (f.path, f.line, f.pass_id, f.message))
    return LintReport(
        findings=kept,
        passes_run=selected,
        files_scanned=len(ctx.modules),
        suppressions_used=len(used),
    )
