"""CLI: ``python -m tpu_render_cluster.lint`` (or ``scripts/lint.py`` from
a bare checkout). Exit 0 when clean, 1 on findings, 2 on usage errors."""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from tpu_render_cluster.lint import PASSES, lint_package


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpu_render_cluster.lint",
        description=(
            "trc-lint: event-loop blocking, wire-schema conformance, "
            "jit purity, and the TRC_* env registry, over the whole package."
        ),
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable report on stdout"
    )
    parser.add_argument(
        "--passes",
        default=None,
        help=f"comma-separated subset of: {', '.join(PASSES)}",
    )
    parser.add_argument(
        "--package-root",
        type=Path,
        default=None,
        help="package directory to lint (default: the installed "
        "tpu_render_cluster package)",
    )
    parser.add_argument(
        "--repo-root",
        type=Path,
        default=None,
        help="repo root holding README.md / PROTOCOL.md "
        "(default: the package root's parent)",
    )
    parser.add_argument(
        "--list-passes", action="store_true", help="list pass ids and exit"
    )
    args = parser.parse_args(argv)

    if args.list_passes:
        for pass_id, fn in PASSES.items():
            doc = (sys.modules[fn.__module__].__doc__ or "").strip()
            print(f"{pass_id}: {doc.splitlines()[0]}")
        return 0

    pass_ids = None
    if args.passes:
        pass_ids = tuple(p.strip() for p in args.passes.split(",") if p.strip())
        unknown = [p for p in pass_ids if p not in PASSES]
        if unknown:
            parser.error(f"unknown pass(es): {', '.join(unknown)}")

    report = lint_package(
        package_root=args.package_root,
        repo_root=args.repo_root,
        pass_ids=pass_ids,
    )
    print(report.to_json() if args.json else report.format())
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
