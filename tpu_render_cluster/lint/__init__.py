"""trc-lint: the codebase-native static-analysis suite (ARCHITECTURE §L12).

Five passes enforce the conventions the cluster's correctness rests on —
``loop-blocking`` (never block the asyncio event loop), ``wire-schema``
(the optional-key omitted-when-absent idiom, checked against
``protocol/schema.py`` and PROTOCOL.md), ``jit-purity`` (no host effects
inside traced render functions), ``env-registry`` (every ``TRC_*``
knob declared in ``utils/env.py`` and documented in README), and
``env-tiers`` (static jit-arg env tiers — the BVH node-format knobs —
resolve outside traced functions only) — plus the ``pragma`` meta-pass
that keeps every suppression explained.

Run it: ``python -m tpu_render_cluster.lint`` (``--json`` for machine
output; nonzero exit on findings). The whole suite is a tier-1 gate
(``tests/test_lint.py``), the same shape as the metric naming lint.
"""

from __future__ import annotations

from tpu_render_cluster.lint import (
    env_registry,
    env_tiers,
    jit_purity,
    loop_blocking,
    wire_schema,
)
from tpu_render_cluster.lint.core import (
    Finding,
    LintContext,
    LintReport,
    Pragma,
    SourceModule,
    discover_modules,
    run_lint,
)

PASSES = {
    loop_blocking.PASS_ID: loop_blocking.run,
    wire_schema.PASS_ID: wire_schema.run,
    jit_purity.PASS_ID: jit_purity.run,
    env_registry.PASS_ID: env_registry.run,
    env_tiers.PASS_ID: env_tiers.run,
}

__all__ = [
    "Finding",
    "LintContext",
    "LintReport",
    "PASSES",
    "Pragma",
    "SourceModule",
    "discover_modules",
    "lint_package",
    "run_lint",
]


def lint_package(
    package_root=None,
    repo_root=None,
    pass_ids=None,
    **overrides,
) -> LintReport:
    """One-call entry: lint the (real or fixture) package tree."""
    ctx = LintContext.for_package(package_root, repo_root, **overrides)
    return run_lint(ctx, PASSES, pass_ids)
