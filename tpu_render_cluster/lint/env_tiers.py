"""Pass ``env-tiers``: static jit-arg env tiers resolve OUTSIDE traced
functions.

The BVH node-format knobs (``TRC_TLAS``/``TRC_TLAS_LEAF``/
``TRC_TLAS_BLOCK``/``TRC_BVH_QUANT``/``TRC_BVH_BUILDER``/
``TRC_BVH_WIDE``) select between distinct compiled programs: their
values are threaded into jit identities as STATIC arguments, renderer
cache keys, and geometry-build memo keys. Reading one of their tier
helpers from inside a traced function would bake the first trace's
environment into the executable — the toggle-mid-process staleness bug
the resolved-outside contract (integrator.resolve_bvh_config and the
driver-level reads) exists to prevent, and exactly what lets the
interleaved ``bench.py --bvh-compare`` hold every variant in one
process.

This pass finds the traced functions with the same static analysis as
``jit-purity`` (decorated defs, defs passed to ``jit``/``pallas_call``/
``shard_map``, factory-returned closures) and flags any call to a
declared tier-reader helper inside one. Like ``jit-purity``, the scan
is BODY-LOCAL — a tier read buried one plain-function call below a
traced def is not reachable statically, so the drivers additionally
thread the resolved values as explicit (static) arguments all the way
down (``tlas_block``/``quant``/``builder``/``wide`` parameters on the
bounce/pool drivers); the pass catches the direct regressions, the
threading convention covers the rest. Helpers that are *dispatch*
tiers read per call by documented design (``pallas_enabled``,
``wavefront_mode``, ``raypool_mode``) are not in the set — they select
a driver, not a compiled program's static configuration.
"""

from __future__ import annotations

import ast

from tpu_render_cluster.lint.core import Finding, LintContext, SourceModule
from tpu_render_cluster.lint.jit_purity import _traced_defs

PASS_ID = "env-tiers"

# The static-jit-arg tier readers: functions whose return value must be
# threaded INTO a traced function, never read from within one.
TIER_READERS = {
    "tlas_enabled",
    "tlas_leaf_size",
    "tlas_block_r",
    "bvh_quant_mode",
    "bvh_builder",
    "bvh_wide",
    "resolve_bvh_config",
}


def _callee_name(node: ast.expr) -> str | None:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


class _TierCallScanner(ast.NodeVisitor):
    def __init__(self, module: SourceModule, qualname: str):
        self.module = module
        self.qualname = qualname
        self.findings: list[Finding] = []

    def visit_Call(self, node: ast.Call):  # noqa: N802
        name = _callee_name(node.func)
        if name in TIER_READERS:
            self.findings.append(
                Finding(
                    PASS_ID,
                    self.module.relpath,
                    node.lineno,
                    f"traced function {self.qualname!r} reads the static "
                    f"jit-arg env tier via {name}() — the value would be "
                    "baked at first trace; resolve it in the untraced "
                    "driver/factory (integrator.resolve_bvh_config) and "
                    "thread it in as a static argument",
                )
            )
        self.generic_visit(node)


def run(ctx: LintContext) -> list[Finding]:
    # Package-wide def index for cross-module factory resolution (the
    # same shape as jit_purity.run — both passes must agree on which
    # defs are traced).
    package_defs: dict[str, list[ast.AST]] = {}
    def_module: dict[int, SourceModule] = {}
    for module in ctx.modules:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.FunctionDef):
                package_defs.setdefault(node.name, []).append(node)
                def_module[id(node)] = module

    findings: list[Finding] = []
    seen: set[int] = set()
    for module in ctx.modules:
        for node in _traced_defs(module, package_defs):
            if id(node) in seen:
                continue
            seen.add(id(node))
            owner = def_module.get(id(node), module)
            scanner = _TierCallScanner(owner, node.name)
            for child in ast.iter_child_nodes(node):
                scanner.visit(child)
            findings.extend(scanner.findings)
    return findings
