"""Cooperative cancellation token.

Semantics follow the reference's clonable atomic-bool token that every task
loop polls (reference: shared/src/cancellation.rs:5-24). This implementation
additionally exposes an asyncio-friendly wait so loops can block on
"cancelled OR timeout" instead of busy-polling.
"""

from __future__ import annotations

import asyncio
import threading


class CancellationToken:
    """Thread-safe, clonable-by-reference cancellation flag.

    Async waiters register an ``asyncio.Event`` waker that ``cancel()`` sets
    via ``loop.call_soon_threadsafe`` — no polling, and cancellation is
    observed immediately from any thread.
    """

    __slots__ = ("_event", "_lock", "_wakers")

    def __init__(self) -> None:
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._wakers: list[tuple[asyncio.AbstractEventLoop, asyncio.Event]] = []

    def cancel(self) -> None:
        with self._lock:
            self._event.set()
            wakers, self._wakers = self._wakers, []
        for loop, event in wakers:
            try:
                loop.call_soon_threadsafe(event.set)
            except RuntimeError:
                pass  # loop already closed

    def is_cancelled(self) -> bool:
        return self._event.is_set()

    async def wait_cancelled(self, timeout: float | None = None) -> bool:
        """Asynchronously wait until cancelled (or timeout); returns is_cancelled."""
        if self._event.is_set():
            return True
        loop = asyncio.get_running_loop()
        waker = asyncio.Event()
        entry = (loop, waker)
        with self._lock:
            if self._event.is_set():
                return True
            self._wakers.append(entry)
        try:
            await asyncio.wait_for(waker.wait(), timeout)
        except asyncio.TimeoutError:
            pass
        finally:
            with self._lock:
                if entry in self._wakers:
                    self._wakers.remove(entry)
        return self._event.is_set()
