from tpu_render_cluster.utils.cancellation import CancellationToken
from tpu_render_cluster.utils.paths import (
    parse_with_base_directory_prefix,
    parse_with_tilde_support,
)

__all__ = [
    "CancellationToken",
    "parse_with_base_directory_prefix",
    "parse_with_tilde_support",
]
