"""Console + optional file logging initialisation.

Mirrors the reference's tracing-subscriber setup: console layer with an
env-var level filter plus an optional non-blocking file layer
(reference: shared/src/logging.rs:39-96). The env filter variable is
``TRC_LOG`` (the reference uses ``RUST_LOG``); both are honoured.
"""

from __future__ import annotations

import logging
import os
import sys
from pathlib import Path
from tpu_render_cluster.utils.env import env_str

_LEVELS = {
    "trace": logging.DEBUG,  # python has no TRACE; map to DEBUG
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
}

_FORMAT = "%(asctime)s %(levelname)-5s %(name)s: %(message)s"


def _env_level(default: str = "info") -> int:
    raw = env_str("TRC_LOG") or os.environ.get("RUST_LOG") or default
    # The global level is the first directive WITHOUT a module prefix
    # (e.g. "tungstenite=warn,info" -> "info"); per-module filters are ignored.
    level = default
    for directive in raw.split(","):
        directive = directive.strip().lower()
        if directive and "=" not in directive:
            level = directive
            break
    return _LEVELS.get(level, logging.INFO)


def initialize_console_and_file_logging(
    log_file_path: str | Path | None = None,
    *,
    console_level: int | None = None,
) -> logging.Logger:
    """Set up the root logger with a console handler and optional file handler.

    Returns the root logger (the reference returns a flush guard; Python's
    logging flushes on process exit, so no guard is needed).
    """
    root = logging.getLogger()
    root.setLevel(logging.DEBUG)
    # Re-initialisation replaces handlers (tests call this repeatedly).
    for handler in list(root.handlers):
        root.removeHandler(handler)

    console = logging.StreamHandler(sys.stderr)
    console.setLevel(console_level if console_level is not None else _env_level())
    console.setFormatter(logging.Formatter(_FORMAT))
    root.addHandler(console)

    if log_file_path is not None:
        path = Path(log_file_path)
        path.parent.mkdir(parents=True, exist_ok=True)
        file_handler = logging.FileHandler(path, encoding="utf-8")
        file_handler.setLevel(logging.DEBUG)
        file_handler.setFormatter(logging.Formatter(_FORMAT))
        root.addHandler(file_handler)

    return root


class WorkerLogger(logging.LoggerAdapter):
    """Logger adapter adding worker id + address context to every record.

    Reference: master/src/connection/worker_logger.rs:11-129.
    """

    def __init__(self, logger: logging.Logger, worker_id: str, address: str) -> None:
        super().__init__(logger, {"worker_id": worker_id, "address": address})

    def process(self, msg, kwargs):
        return f"[worker_id={self.extra['worker_id']} address={self.extra['address']}] {msg}", kwargs
