"""Path placeholder utilities.

Job files are portable across nodes with a shared filesystem by using the
``%BASE%`` placeholder, resolved per-worker against its ``--baseDirectory``
(reference: worker/src/utilities.rs:5-37).
"""

from __future__ import annotations

import os
from pathlib import Path

BASE_PLACEHOLDER = "%BASE%"


def parse_with_tilde_support(path: str) -> Path:
    """Expand a leading ``~`` using the HOME environment variable."""
    if path == "~" or path.startswith("~/") or path.startswith("~\\"):
        home = os.environ.get("HOME")
        if not home:
            raise ValueError("Cannot expand '~': HOME is not set.")
        return Path(home) / path[2:] if len(path) > 1 else Path(home)
    return Path(path)


def parse_with_base_directory_prefix(path: str, base_directory: Path | str | None) -> Path:
    """Resolve the %BASE% placeholder against the worker's base directory."""
    if path.startswith(BASE_PLACEHOLDER):
        if base_directory is None:
            raise ValueError(f"Path {path!r} uses %BASE% but no base directory was provided.")
        remainder = path[len(BASE_PLACEHOLDER):].lstrip("/\\")
        return Path(base_directory) / remainder
    return parse_with_tilde_support(path)
