"""Timestamp helpers.

All timestamps in the protocol and trace schema are fractional unix seconds
(f64), matching the reference's ``TimestampSecondsWithFrac<f64>`` serde and
the analysis suite's ``datetime.fromtimestamp(float)`` parsing
(reference: shared/src/results/worker_trace.rs:12-34,
analysis/core/models.py:62-68).
"""

from __future__ import annotations

import time
from datetime import datetime, timezone


def now_ts() -> float:
    """Current time as fractional unix seconds."""
    return time.time()


def ts_to_datetime(ts: float) -> datetime:
    return datetime.fromtimestamp(ts, tz=timezone.utc)


def datetime_to_ts(dt: datetime) -> float:
    return dt.timestamp()
