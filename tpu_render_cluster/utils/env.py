"""``TRC_*`` environment overrides for runtime tuning knobs.

The transport deadlines, retry caps, and heartbeat tolerances all ship
reference-derived defaults but are consulted through these helpers so a
deployment (or the chaos harness, which compresses every timeout to keep
fault scenarios fast) can retune them without code changes. Values are
read at *call* time, not import time: long-lived processes and tests that
monkeypatch ``os.environ`` both see the current value.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger(__name__)


def env_float(name: str, default: float) -> float:
    """``float(os.environ[name])`` with a logged fallback on bad values."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return float(raw)
    except ValueError:
        logger.warning("Ignoring non-numeric %s=%r; using %s", name, raw, default)
        return default


def env_int(name: str, default: int) -> int:
    """``int(os.environ[name])`` with a logged fallback on bad values."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return int(raw)
    except ValueError:
        logger.warning("Ignoring non-integer %s=%r; using %s", name, raw, default)
        return default
