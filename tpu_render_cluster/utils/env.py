"""``TRC_*`` environment overrides for runtime tuning knobs — the registry.

The transport deadlines, retry caps, and heartbeat tolerances all ship
reference-derived defaults but are consulted through these helpers so a
deployment (or the chaos harness, which compresses every timeout to keep
fault scenarios fast) can retune them without code changes. Values are
read at *call* time, not import time: long-lived processes and tests that
monkeypatch ``os.environ`` both see the current value.

This module is also the single place a ``TRC_*`` variable may touch
``os.environ``, and the single place every variable is DECLARED: the
``env-registry`` lint pass (``tpu_render_cluster/lint/env_registry.py``)
refuses direct ``os.environ`` reads of ``TRC_*`` names elsewhere in the
package, refuses helper reads of names missing from :data:`ENV_VARS`,
and cross-checks the registry against README.md's environment tables —
an undeclared read, a double declaration, a dead declaration, and a
missing README row are all tier-1 failures.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# Declarations


@dataclass(frozen=True)
class EnvVar:
    """One declared ``TRC_*`` knob (name, value grammar, one-line doc)."""

    name: str
    kind: str  # "int" | "float" | "str" | "flag" | "path" | "port" | "spec"
    default: object
    doc: str


ENV_VARS: dict[str, EnvVar] = {}


def declare(name: str, kind: str, default: object, doc: str) -> None:
    """Register one variable; a duplicate declaration is a programming
    error (and an ``env-registry`` lint finding) rather than a silent
    overwrite."""
    if name in ENV_VARS:
        raise ValueError(f"duplicate env declaration: {name}")
    ENV_VARS[name] = EnvVar(name, kind, default, doc)


# -- transport / reconnect ---------------------------------------------------
declare("TRC_BACKOFF_BASE", "float", 2.0, "Full-jitter reconnect backoff base")
declare("TRC_BACKOFF_CAP_SECONDS", "float", 30.0, "Reconnect backoff sleep cap")
declare("TRC_MAX_CONNECT_RETRIES", "int", 12, "Connect attempts before giving up")
declare("TRC_MAX_RECONNECTS_PER_OP", "int", 2, "Reconnects one logical op may absorb")
declare("TRC_OP_DEADLINE_SECONDS", "float", 30.0, "Per-op reconnect deadline")
declare("TRC_SEND_DEADLINE_SECONDS", "float", 45.0, "Master->worker send deadline")
declare("TRC_RPC_DEADLINE_SECONDS", "float", 60.0, "Master->worker ack deadline")
declare("TRC_HEARTBEAT_PONG_RETRIES", "int", 1, "Extra pings after a missed pong")
# -- master / units ----------------------------------------------------------
declare("TRC_MAX_UNIT_ERRORS", "int", 8, "Deterministic render errors per unit before the job fails")
# -- render tiers ------------------------------------------------------------
declare("TRC_PALLAS", "flag", None, "Pallas kernel dispatch override (1/0; unset = TPU only)")
declare("TRC_WAVEFRONT", "spec", "auto", "Wavefront tier: auto | force | off")
declare("TRC_RAYPOOL", "spec", "auto", "Device-resident ray-pool tier: auto | force | off")
declare("TRC_RAYPOOL_FRAMES", "int", 8, "Frames per compiled pool window")
declare("TRC_RAYPOOL_WIDTH", "int", None, "Ray-pool width (default: one frame, block-rounded)")
declare("TRC_TLAS", "flag", 1, "Two-level (TLAS) mesh traversal on/off")
declare("TRC_TLAS_LEAF", "int", 4, "Instances per TLAS leaf (clamped 1..16)")
declare("TRC_TLAS_BLOCK", "int", 256, "Ray-block width of the TLAS kernel variants")
declare("TRC_BVH_QUANT", "int", 0, "Quantized BVH/TLAS node tier: 0 off, 1 16-bit, 2 8-bit slabs (+ packed carried ray state)")
declare("TRC_BVH_BUILDER", "spec", "sah", "BLAS build strategy: sah (binned) | median")
declare("TRC_BVH_WIDE", "int", 4, "BLAS branching factor after wide collapse (1 = binary, clamped 1..8)")
declare("TRC_COMPILE_CACHE", "path", None, "Persistent XLA compile cache directory")
# -- jobs / tiles ------------------------------------------------------------
declare("TRC_TILE_GRID", "spec", None, "Default RxC tile grid applied at job load time")
# -- logging / analysis paths ------------------------------------------------
declare("TRC_LOG", "spec", None, "Log level/filter (RUST_LOG grammar; RUST_LOG also accepted)")
declare("TRC_RESULTS_ROOT", "path", None, "Root for experiment results")
declare("TRC_RESULTS_DIR", "path", None, "Cluster-run trace directory")
declare("TRC_ANALYSIS_DIR", "path", None, "Analysis output directory")
# -- chaos -------------------------------------------------------------------
declare("TRC_CHAOS_SEED", "int", 0, "Default fault-plan seed for FaultPlan.from_env()")
declare("TRC_CHAOS_WORKERS", "int", 3, "Default fault-plan worker count")
declare("TRC_CHAOS_PLAN", "path", None, "Fault-plan TOML path (wins over seed/workers)")
# -- scheduler ---------------------------------------------------------------
declare("TRC_SCHED_TICK_SECONDS", "float", 0.05, "Scheduler dispatch/admission tick")
declare("TRC_SCHED_TARGET_QUEUE_SIZE", "int", 2, "In-flight slots per live worker")
declare("TRC_SCHED_MAX_ACTIVE_JOBS", "int", 4, "Concurrently running jobs")
declare("TRC_SCHED_PREEMPTION", "flag", 1, "Preemption of over-share jobs on/off")
declare("TRC_SCHED_MAX_PREEMPTIONS_PER_TICK", "int", 1, "Preemptions per scheduler tick")
declare("TRC_SCHED_DRAIN_GRACE_SECONDS", "float", 10.0, "Drain grace before cancelling barrier-unadmittable jobs")
declare("TRC_SCHED_TICK", "spec", "heap", "Tick pick structure: heap | scan (legacy full rescan) | verify (heap + scan cross-check)")
declare("TRC_DISPATCH_FRAMES", "spec", "cached", "Dispatch frame encoding: cached (preserialized splice) | encode (per-send JSON)")
# -- cost model / speculation ------------------------------------------------
declare("TRC_COST_MODEL", "path", None, "Trace-trained cost model loaded at master start")
declare("TRC_SPECULATION", "flag", 0, "Straggler-aware speculative re-execution on/off")
declare("TRC_SPEC_THRESHOLD", "float", 2.0, "Tail-score multiple of p50 that triggers a hedge")
declare("TRC_SPEC_MIN_SAMPLES", "int", 3, "Cost-model observations before prediction-triggered hedging")
declare("TRC_SPEC_MAX_ACTIVE", "int", 2, "Concurrent speculative twins per job")
# -- telemetry / SLO ---------------------------------------------------------
declare("TRC_OBS_PORT", "port", None, "Master /metrics + /healthz + /clusterz port")
declare("TRC_OBS_WORKER_PORT", "port", None, "Worker /metrics + /healthz port")
declare("TRC_OBS_ROUTER_PORT", "port", None, "Shard router federated telemetry port")
declare("TRC_OBS_PROFILING", "flag", 1, "Kernel roofline cost capture on/off")
declare("TRC_PEAK_FLOPS", "float", None, "Roofline peak FLOP/s override")
declare("TRC_PEAK_BYTES_PER_SECOND", "float", None, "Roofline peak bytes/s override")
declare("TRC_SLO_SHORT_WINDOW_SECONDS", "float", 60.0, "SLO burn short window")
declare("TRC_SLO_LONG_WINDOW_SECONDS", "float", 300.0, "SLO burn long window")
declare("TRC_SLO_BURN_THRESHOLD", "float", 1.0, "Burn ratio that counts as breaching")
declare("TRC_SLO_MIN_WINDOW_SAMPLES", "int", 1, "Observations a window needs before it may breach")
declare("TRC_SLO_TICK_SECONDS", "float", 0.5, "Periodic SLO evaluation interval")
# -- continuous observability ------------------------------------------------
declare("TRC_OBS_HISTORY_INTERVAL", "float", 1.0, "Metrics-history sampling interval")
declare("TRC_OBS_HISTORY_RETENTION", "float", 600.0, "Metrics-history ring reach (seconds)")
declare("TRC_OBS_FLIGHT_SECONDS", "float", 60.0, "Flight-recorder bundle window")
declare("TRC_OBS_FLIGHT_DEBOUNCE", "float", 5.0, "Min spacing between dumps per trigger kind")
declare("TRC_OBS_FLIGHT_EVENTS", "int", 4096, "Flight-recorder protocol-digest ring size")
declare("TRC_OBS_FLIGHT_DIR", "path", None, "Blackbox bundle directory")
declare("TRC_OBS_LOOPMON_INTERVAL", "float", 0.25, "Event-loop lag probe interval")
declare("TRC_OBS_LOOPMON_THRESHOLD", "float", 0.1, "Loop lag that counts as a blocked episode")
declare("TRC_SCHED_PROFILE", "flag", 1, "Scheduler tick phase profiling on/off")
# -- replicated control plane ------------------------------------------------
declare("TRC_HA_LEDGER", "path", None, "Write-ahead job ledger directory (master --ledger default)")
declare("TRC_HA_FSYNC", "flag", 1, "fsync after every ledger append")
declare("TRC_HA_SEGMENT_RECORDS", "int", 4096, "Ledger records per segment before rotation")
declare("TRC_HA_SNAPSHOT_EVERY", "int", 8192, "Appends between automatic ledger snapshots (0 off)")
declare("TRC_HA_REPL_PORT", "port", None, "Ledger streaming-replication listen port (master --replicationPort default)")
declare("TRC_HA_REPL_ACK_EVERY", "int", 32, "Applied records between follower cumulative acks")
declare("TRC_HA_REPL_RETRY_SECONDS", "float", 0.5, "Follower reconnect delay after a broken replication stream")
declare("TRC_HA_REPL_PROBE_SECONDS", "float", 0.5, "Router shard-liveness probe interval")
declare("TRC_HA_REPL_PROMOTE_TIMEOUT", "float", 2.0, "Unreachable-primary window before the router promotes a follower")
# -- live shard rebalancing ---------------------------------------------------
declare("TRC_REBALANCE", "flag", 0, "Router-driven hot->cold worker rebalancing on/off")
declare("TRC_REBALANCE_INTERVAL_SECONDS", "float", 5.0, "Rebalancer scrape/decide tick interval")
declare("TRC_REBALANCE_THRESHOLD", "float", 2.0, "Hot/cold per-worker load ratio that counts as imbalanced")
declare("TRC_REBALANCE_HYSTERESIS_TICKS", "int", 3, "Consecutive imbalanced ticks before the first move")
declare("TRC_REBALANCE_COOLDOWN_SECONDS", "float", 30.0, "Min spacing between rebalance moves")
declare("TRC_REBALANCE_MAX_MOVES", "int", 2, "Max workers migrated per rebalance move")


# ---------------------------------------------------------------------------
# Readers (consulted at call time, never cached)


def env_float(name: str, default: float) -> float:
    """``float(os.environ[name])`` with a logged fallback on bad values."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return float(raw)
    except ValueError:
        logger.warning("Ignoring non-numeric %s=%r; using %s", name, raw, default)
        return default


def env_int(name: str, default: int) -> int:
    """``int(os.environ[name])`` with a logged fallback on bad values."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return int(raw)
    except ValueError:
        logger.warning("Ignoring non-integer %s=%r; using %s", name, raw, default)
        return default


def env_str(name: str, default: str | None = None) -> str | None:
    """Raw string value, or ``default`` when unset.

    Unlike the numeric readers an empty string is returned as-is: several
    knobs (``TRC_TILE_GRID``, ``TRC_COST_MODEL``) treat ``""`` and unset
    identically by stripping at the call site, while others distinguish
    unset (``None``) from an explicit value.
    """
    raw = os.environ.get(name)
    return default if raw is None else raw
