"""Master-side trace (reference: shared/src/results/master_trace.rs:7-24)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any


@dataclass(frozen=True)
class MasterTrace:
    job_start_time: float
    job_finish_time: float

    def job_duration(self) -> float:
        return self.job_finish_time - self.job_start_time

    def to_dict(self) -> dict[str, float]:
        return {
            "job_start_time": self.job_start_time,
            "job_finish_time": self.job_finish_time,
        }

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "MasterTrace":
        return cls(
            job_start_time=float(data["job_start_time"]),
            job_finish_time=float(data["job_finish_time"]),
        )
