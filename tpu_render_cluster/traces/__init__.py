from tpu_render_cluster.traces.master_trace import MasterTrace
from tpu_render_cluster.traces.performance import WorkerPerformance
from tpu_render_cluster.traces.worker_trace import (
    FrameRenderTime,
    WorkerFrameTrace,
    WorkerPingTrace,
    WorkerReconnectionTrace,
    WorkerTrace,
    WorkerTraceBuilder,
)

__all__ = [
    "MasterTrace",
    "WorkerPerformance",
    "FrameRenderTime",
    "WorkerFrameTrace",
    "WorkerPingTrace",
    "WorkerReconnectionTrace",
    "WorkerTrace",
    "WorkerTraceBuilder",
]
