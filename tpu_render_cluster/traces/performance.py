"""Per-worker performance reducer.

Folds a ``WorkerTrace`` into totals, matching the reference's metric contract
exactly (reference: shared/src/results/performance.rs:12-144), including its
idle-time definition: lead-in before the first frame, tail after the last
frame, and gaps between consecutive middle frames. Note the reference's
branch ordering means the last frame's gap to its predecessor is *not*
counted — we replicate that deliberately since processed-results numbers are
part of the metric contract. Durations serialise as fractional seconds
(``DurationSecondsWithFrac<f64>`` equivalence).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from tpu_render_cluster.traces.worker_trace import WorkerTrace


def _nonnegative(value: float, what: str) -> float:
    if value < 0:
        raise ValueError(f"{what} is negative ({value} s).")
    return value


@dataclass(frozen=True)
class WorkerPerformance:
    total_frames_rendered: int
    total_frames_queued: int
    total_frames_stolen_from_queue: int
    total_times_reconnected: int
    total_time: float
    total_blend_file_reading_time: float
    total_rendering_time: float
    total_image_saving_time: float
    total_idle_time: float

    @classmethod
    def from_worker_trace(cls, trace: WorkerTrace) -> "WorkerPerformance":
        total_time = _nonnegative(
            trace.job_finish_time - trace.job_start_time, "Total job duration"
        )

        reading = 0.0
        rendering = 0.0
        saving = 0.0
        idle = 0.0

        frames = trace.frame_render_traces
        for i, frame in enumerate(frames):
            d = frame.details
            reading += _nonnegative(
                d.finished_loading_at - d.started_process_at, "File reading duration"
            )
            rendering += _nonnegative(
                d.finished_rendering_at - d.started_rendering_at, "Rendering duration"
            )
            saving += _nonnegative(
                d.file_saving_finished_at - d.file_saving_started_at, "File saving duration"
            )
            if i == 0:
                idle += _nonnegative(
                    d.started_process_at - trace.job_start_time,
                    "Idle time before first frame",
                )
            elif i == len(frames) - 1:
                idle += _nonnegative(
                    trace.job_finish_time - d.exited_process_at,
                    "Idle time after last frame",
                )
            else:
                idle += _nonnegative(
                    d.started_process_at - frames[i - 1].details.exited_process_at,
                    "Idle time between frames",
                )

        return cls(
            total_frames_rendered=len(frames),
            total_frames_queued=trace.total_queued_frames,
            total_frames_stolen_from_queue=trace.total_queued_frames_removed_from_queue,
            total_times_reconnected=len(trace.reconnection_traces),
            total_time=total_time,
            total_blend_file_reading_time=reading,
            total_rendering_time=rendering,
            total_image_saving_time=saving,
            total_idle_time=idle,
        )

    def to_dict(self) -> dict[str, Any]:
        return {
            "total_frames_rendered": self.total_frames_rendered,
            "total_frames_queued": self.total_frames_queued,
            "total_frames_stolen_from_queue": self.total_frames_stolen_from_queue,
            "total_times_reconnected": self.total_times_reconnected,
            "total_time": self.total_time,
            "total_blend_file_reading_time": self.total_blend_file_reading_time,
            "total_rendering_time": self.total_rendering_time,
            "total_image_saving_time": self.total_image_saving_time,
            "total_idle_time": self.total_idle_time,
        }
