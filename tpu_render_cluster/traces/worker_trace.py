"""Worker-side trace models.

JSON schema is byte-compatible with the reference so the analysis suite
parses our raw traces unchanged: every timestamp serialises as fractional
unix seconds (f64), matching ``TimestampSecondsWithFrac<f64>``
(reference: shared/src/results/worker_trace.rs:12-147; parsed by
analysis/core/models.py:46-131).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any


@dataclass(frozen=True)
class FrameRenderTime:
    """The 7-point per-frame phase timing.

    Reference: shared/src/results/worker_trace.rs:13-34. Timestamps are
    fractional unix seconds.
    """

    started_process_at: float
    finished_loading_at: float
    started_rendering_at: float
    finished_rendering_at: float
    file_saving_started_at: float
    file_saving_finished_at: float
    exited_process_at: float

    def total_execution_time(self) -> float:
        duration = self.exited_process_at - self.started_process_at
        if duration < 0:
            raise ValueError("Total execution time is negative?!")
        return duration

    def to_dict(self) -> dict[str, float]:
        return {
            "started_process_at": self.started_process_at,
            "finished_loading_at": self.finished_loading_at,
            "started_rendering_at": self.started_rendering_at,
            "finished_rendering_at": self.finished_rendering_at,
            "file_saving_started_at": self.file_saving_started_at,
            "file_saving_finished_at": self.file_saving_finished_at,
            "exited_process_at": self.exited_process_at,
        }

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "FrameRenderTime":
        return cls(
            started_process_at=float(data["started_process_at"]),
            finished_loading_at=float(data["finished_loading_at"]),
            started_rendering_at=float(data["started_rendering_at"]),
            finished_rendering_at=float(data["finished_rendering_at"]),
            file_saving_started_at=float(data["file_saving_started_at"]),
            file_saving_finished_at=float(data["file_saving_finished_at"]),
            exited_process_at=float(data["exited_process_at"]),
        )


@dataclass(frozen=True)
class WorkerFrameTrace:
    """A rendered frame's index + phase details (worker_trace.rs:48-63)."""

    frame_index: int
    details: FrameRenderTime

    def to_dict(self) -> dict[str, Any]:
        return {"frame_index": self.frame_index, "details": self.details.to_dict()}

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "WorkerFrameTrace":
        return cls(
            frame_index=int(data["frame_index"]),
            details=FrameRenderTime.from_dict(data["details"]),
        )


@dataclass(frozen=True)
class WorkerPingTrace:
    """Heartbeat RTT sample (worker_trace.rs:65-82)."""

    pinged_at: float
    received_at: float

    def latency(self) -> float:
        return max(0.0, self.received_at - self.pinged_at)

    def to_dict(self) -> dict[str, float]:
        return {"pinged_at": self.pinged_at, "received_at": self.received_at}

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "WorkerPingTrace":
        return cls(pinged_at=float(data["pinged_at"]), received_at=float(data["received_at"]))


@dataclass(frozen=True)
class WorkerReconnectionTrace:
    """A connection-loss window (worker_trace.rs:84-100)."""

    lost_connection_at: float
    reconnected_at: float

    def to_dict(self) -> dict[str, float]:
        return {
            "lost_connection_at": self.lost_connection_at,
            "reconnected_at": self.reconnected_at,
        }

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "WorkerReconnectionTrace":
        return cls(
            lost_connection_at=float(data["lost_connection_at"]),
            reconnected_at=float(data["reconnected_at"]),
        )


@dataclass(frozen=True)
class WorkerTrace:
    """Aggregate worker trace, carried by ``response_job-finished``.

    Reference: shared/src/results/worker_trace.rs:103-126.
    """

    total_queued_frames: int
    total_queued_frames_removed_from_queue: int
    job_start_time: float
    job_finish_time: float
    frame_render_traces: list[WorkerFrameTrace]
    ping_traces: list[WorkerPingTrace]
    reconnection_traces: list[WorkerReconnectionTrace]

    def to_dict(self) -> dict[str, Any]:
        return {
            "total_queued_frames": self.total_queued_frames,
            "total_queued_frames_removed_from_queue": self.total_queued_frames_removed_from_queue,
            "job_start_time": self.job_start_time,
            "job_finish_time": self.job_finish_time,
            "frame_render_traces": [t.to_dict() for t in self.frame_render_traces],
            "ping_traces": [t.to_dict() for t in self.ping_traces],
            "reconnection_traces": [t.to_dict() for t in self.reconnection_traces],
        }

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "WorkerTrace":
        return cls(
            total_queued_frames=int(data["total_queued_frames"]),
            total_queued_frames_removed_from_queue=int(
                data["total_queued_frames_removed_from_queue"]
            ),
            job_start_time=float(data["job_start_time"]),
            job_finish_time=float(data["job_finish_time"]),
            frame_render_traces=[
                WorkerFrameTrace.from_dict(t) for t in data["frame_render_traces"]
            ],
            ping_traces=[WorkerPingTrace.from_dict(t) for t in data["ping_traces"]],
            reconnection_traces=[
                WorkerReconnectionTrace.from_dict(t) for t in data["reconnection_traces"]
            ],
        )


class WorkerTraceBuilder:
    """Thread-safe incremental trace collector.

    A single builder instance is threaded through the worker's runner, queue,
    heartbeat responder, and client (reference:
    shared/src/results/worker_trace.rs:149-237). ``build`` refuses
    incomplete traces (missing start/finish), matching the reference's
    builder semantics (worker_trace.rs:165-181).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._total_queued_frames = 0
        self._total_removed = 0
        self._job_start_time: float | None = None
        self._job_finish_time: float | None = None
        self._frame_render_traces: list[WorkerFrameTrace] = []
        self._ping_traces: list[WorkerPingTrace] = []
        self._reconnection_traces: list[WorkerReconnectionTrace] = []

    def trace_new_rendered_frame(self, frame_index: int, timing: FrameRenderTime) -> None:
        with self._lock:
            self._frame_render_traces.append(WorkerFrameTrace(frame_index, timing))

    def trace_new_ping(self, pinged_at: float, received_at: float) -> None:
        with self._lock:
            self._ping_traces.append(WorkerPingTrace(pinged_at, received_at))

    def trace_new_reconnect(self, lost_connection_at: float, reconnected_at: float) -> None:
        with self._lock:
            self._reconnection_traces.append(
                WorkerReconnectionTrace(lost_connection_at, reconnected_at)
            )

    def increment_total_queued_frames(self) -> None:
        with self._lock:
            self._total_queued_frames += 1

    def increment_total_frames_removed_from_queue(self) -> None:
        with self._lock:
            self._total_removed += 1

    def set_job_start_time(self, ts: float) -> None:
        with self._lock:
            self._job_start_time = ts

    def ensure_job_start_time(self, ts: float) -> None:
        """Stamp the start time only if no job-started event ever did —
        the close-out path of a worker that served an idle master (a
        drained shard with zero jobs) must still produce a buildable
        trace without clobbering a real job's start."""
        with self._lock:
            if self._job_start_time is None:
                self._job_start_time = ts

    def set_job_finish_time(self, ts: float) -> None:
        with self._lock:
            self._job_finish_time = ts

    def build(self) -> WorkerTrace:
        with self._lock:
            if self._job_start_time is None:
                raise ValueError("Cannot build trace: job start time was never set.")
            if self._job_finish_time is None:
                raise ValueError("Cannot build trace: job finish time was never set.")
            return WorkerTrace(
                total_queued_frames=self._total_queued_frames,
                total_queued_frames_removed_from_queue=self._total_removed,
                job_start_time=self._job_start_time,
                job_finish_time=self._job_finish_time,
                frame_render_traces=list(self._frame_render_traces),
                ping_traces=list(self._ping_traces),
                reconnection_traces=list(self._reconnection_traces),
            )
