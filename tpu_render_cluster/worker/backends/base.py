"""Render backend interface."""

from __future__ import annotations

import abc

from tpu_render_cluster.jobs.models import BlenderJob
from tpu_render_cluster.traces.worker_trace import FrameRenderTime


class RenderBackend(abc.ABC):
    """Renders one frame of a job and reports 7-phase timing.

    Implementations must write the output file to the job's resolved output
    directory and return a ``FrameRenderTime`` whose phases satisfy the
    performance reducer's monotonicity requirements
    (tpu_render_cluster/traces/performance.py).
    """

    @abc.abstractmethod
    async def render_frame(self, job: BlenderJob, frame_index: int) -> FrameRenderTime:
        ...
