"""Render backend interface."""

from __future__ import annotations

import abc

from tpu_render_cluster.jobs.models import BlenderJob
from tpu_render_cluster.traces.worker_trace import FrameRenderTime


class RenderBackend(abc.ABC):
    """Renders one frame of a job and reports 7-phase timing.

    Implementations must write the output file to the job's resolved output
    directory and return a ``FrameRenderTime`` whose phases satisfy the
    performance reducer's monotonicity requirements
    (tpu_render_cluster/traces/performance.py).

    Tiled jobs: when the job carries a tile grid, ``render_frame`` is
    called once per ``(frame, tile)`` work unit with ``tile`` set — the
    backend renders only that tile's pixel region and writes the tile
    file (master/assembly.tile_file_path naming); the master stitches
    the frame. Backends that cannot render sub-frame regions (the
    Blender subprocess backend) must raise a clear error instead of
    silently rendering the whole frame under a tile's name.

    Optional hint protocol: a backend may additionally define
    ``note_upcoming_frames(job, units)``. Before each ``render_frame``
    the worker queue calls it (when present) with the OTHER work units
    (``jobs.tiles.WorkUnit``) of the same job still queued locally —
    the honest work-ahead visible to this worker. Backends that batch
    internally (the tpu-raytrace ray-pool mode renders several queued
    frames in one device program and serves later requests from its
    cache) key off this hint; the one-unit-per-request wire contract is
    unchanged, so masters and peers cannot tell a batching worker from
    a serial one.
    """

    @abc.abstractmethod
    async def render_frame(
        self, job: BlenderJob, frame_index: int, tile: int | None = None
    ) -> FrameRenderTime:
        ...
