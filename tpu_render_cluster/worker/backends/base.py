"""Render backend interface."""

from __future__ import annotations

import abc

from tpu_render_cluster.jobs.models import BlenderJob
from tpu_render_cluster.traces.worker_trace import FrameRenderTime


class RenderBackend(abc.ABC):
    """Renders one frame of a job and reports 7-phase timing.

    Implementations must write the output file to the job's resolved output
    directory and return a ``FrameRenderTime`` whose phases satisfy the
    performance reducer's monotonicity requirements
    (tpu_render_cluster/traces/performance.py).

    Optional hint protocol: a backend may additionally define
    ``note_upcoming_frames(job, frame_indices)``. Before each
    ``render_frame`` the worker queue calls it (when present) with the
    OTHER frames of the same job still queued locally — the honest
    work-ahead visible to this worker. Backends that batch internally
    (the tpu-raytrace ray-pool mode renders several queued frames in
    one device program and serves later requests from its cache) key
    off this hint; the one-frame-per-request wire contract is
    unchanged, so masters and peers cannot tell a batching worker from
    a serial one.
    """

    @abc.abstractmethod
    async def render_frame(self, job: BlenderJob, frame_index: int) -> FrameRenderTime:
        ...
