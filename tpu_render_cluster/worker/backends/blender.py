"""Blender subprocess render backend.

Byte-compatible with the reference worker's runner contract
(reference: worker/src/rendering/runner/mod.rs:18-204):

- CLI: ``blender <file> --background --python <render-script> --
  --render-output <dir/name-format> --render-format <fmt>
  --render-frame <n>`` with shlex-split prepend/append injection;
- stdout scrape (reference: worker/src/rendering/runner/utilities.rs:105-203):
  after the ``Saved: '`` line, a `` Time: mm:ss.ff (Saving: mm:ss.ff)`` line
  yields the save duration and a ``RESULTS={json}`` line from the timing
  script yields loaded/render-start/render-end unix timestamps; the save
  duration is subtracted from render-end to get the true render finish.
"""

from __future__ import annotations

import asyncio
import re
import shlex
import time
from dataclasses import dataclass
from pathlib import Path

from tpu_render_cluster.jobs.models import BlenderJob
from tpu_render_cluster.traces.worker_trace import FrameRenderTime
from tpu_render_cluster.utils.paths import parse_with_base_directory_prefix
from tpu_render_cluster.worker.backends.base import RenderBackend

_TIME_SAVING_RE = re.compile(
    r"Time: (?P<total_time>\d+:\d+\.\d+) \(Saving: (?P<saving_time>\d+:\d+\.\d+)\)"
)
_RESULTS_PREFIX = "RESULTS="


def parse_blender_human_time(text: str) -> float:
    """Parse Blender's ``mm:ss.ff`` duration into seconds."""
    minutes, _, seconds = text.partition(":")
    return int(minutes) * 60 + float(seconds)


@dataclass(frozen=True)
class PartialRenderStatistics:
    loaded_at: float
    started_rendering_at: float
    finished_rendering_at: float
    file_saving_started_at: float
    file_saving_finished_at: float

    def with_process_information(
        self, process_started_at: float, process_exited_at: float
    ) -> FrameRenderTime:
        return FrameRenderTime(
            started_process_at=process_started_at,
            finished_loading_at=self.loaded_at,
            started_rendering_at=self.started_rendering_at,
            finished_rendering_at=self.finished_rendering_at,
            file_saving_started_at=self.file_saving_started_at,
            file_saving_finished_at=self.file_saving_finished_at,
            exited_process_at=process_exited_at,
        )


def extract_blender_render_information(stdout_output: str) -> PartialRenderStatistics:
    """Scrape phase timings from Blender's stdout (see module docstring)."""
    import json

    saving_time: float | None = None
    raw_results: dict | None = None

    lines = iter(stdout_output.splitlines())
    # Skip until the `Saved: '<path>'` line; nothing relevant precedes it.
    for line in lines:
        if line.startswith("Saved: '"):
            break
    else:
        raise ValueError("Invalid Blender output: no \"Saved: '\" line found.")

    for line in lines:
        if line.startswith(" Time:"):
            match = _TIME_SAVING_RE.search(line)
            if match is None:
                continue
            if saving_time is not None:
                raise ValueError(
                    "Invalid Blender output: Time/Saving line appears more than once."
                )
            saving_time = parse_blender_human_time(match.group("saving_time"))
        elif line.startswith(_RESULTS_PREFIX):
            raw_results = json.loads(line[len(_RESULTS_PREFIX):])

    if raw_results is None or saving_time is None:
        raise ValueError(
            f"Invalid Blender output: missing data "
            f"(results={raw_results is not None}, saving_time={saving_time})."
        )

    loaded_at = float(raw_results["project_loaded_at"])
    started_rendering_at = float(raw_results["project_started_rendering_at"])
    finished_with_saving = float(raw_results["project_finished_rendering_at"])
    # The script's render-end includes file saving; subtract it out.
    real_finished_rendering_at = finished_with_saving - saving_time

    return PartialRenderStatistics(
        loaded_at=loaded_at,
        started_rendering_at=started_rendering_at,
        finished_rendering_at=real_finished_rendering_at,
        file_saving_started_at=real_finished_rendering_at,
        file_saving_finished_at=finished_with_saving,
    )


class BlenderBackend(RenderBackend):
    """Runs Blender with the render-timing script and scrapes its stdout."""

    def __init__(
        self,
        *,
        blender_binary: str,
        base_directory: str | Path | None = None,
        prepend_arguments: str | None = None,
        append_arguments: str | None = None,
    ) -> None:
        self.blender_binary = blender_binary
        self.base_directory = Path(base_directory) if base_directory else None
        self.prepend_arguments = shlex.split(prepend_arguments) if prepend_arguments else []
        self.append_arguments = shlex.split(append_arguments) if append_arguments else []

    def _resolve(self, path: str) -> Path:
        return parse_with_base_directory_prefix(path, self.base_directory)

    def build_command(self, job: BlenderJob, frame_index: int) -> list[str]:
        project_file = self._resolve(job.project_file_path)
        render_script = self._resolve(job.render_script_path)
        output_directory = self._resolve(job.output_directory_path)
        render_output = output_directory / job.output_file_name_format
        return [
            self.blender_binary,
            *self.prepend_arguments,
            str(project_file),
            "--background",
            "--python",
            str(render_script),
            "--",
            "--render-output",
            str(render_output),
            "--render-format",
            job.output_file_format,
            "--render-frame",
            str(frame_index),
            *self.append_arguments,
        ]

    async def render_frame(
        self, job: BlenderJob, frame_index: int, tile: int | None = None
    ) -> FrameRenderTime:
        if tile is not None:
            # Blender's CLI renders whole frames; rendering the full frame
            # under a tile's name would make the master stitch N copies of
            # it. The master reschedules the errored unit elsewhere.
            raise RuntimeError(
                "The Blender backend cannot render sub-frame tiles; "
                "run tiled jobs on tpu-raytrace workers."
            )
        project_file = self._resolve(job.project_file_path)
        render_script = self._resolve(job.render_script_path)
        if not project_file.is_file():
            raise FileNotFoundError(f"Project file not found: {project_file}")
        if not render_script.is_file():
            raise FileNotFoundError(f"Render script not found: {render_script}")
        output_directory = self._resolve(job.output_directory_path)
        output_directory.mkdir(parents=True, exist_ok=True)

        command = self.build_command(job, frame_index)
        process_started_at = time.time()
        process = await asyncio.create_subprocess_exec(
            *command,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.DEVNULL,
        )
        stdout, _ = await process.communicate()
        process_exited_at = time.time()
        if process.returncode != 0:
            raise RuntimeError(
                f"Blender exited with code {process.returncode} for frame {frame_index}."
            )
        statistics = extract_blender_render_information(stdout.decode("utf-8", "replace"))
        return statistics.with_process_information(process_started_at, process_exited_at)
