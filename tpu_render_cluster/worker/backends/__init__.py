"""Pluggable render backends.

``blender`` reproduces the reference's subprocess + stdout-scrape contract
(reference: worker/src/rendering/runner/); ``tpu-raytrace`` is the pure
JAX/Pallas path tracer (new, the north-star backend); ``mock`` is the
sleep-based fake renderer used by integration tests (SURVEY.md §4 test
strategy). All emit identical 7-phase ``FrameRenderTime`` traces.
"""

from __future__ import annotations

from tpu_render_cluster.worker.backends.base import RenderBackend


def create_backend(name: str, **kwargs) -> RenderBackend:
    if name == "blender":
        from tpu_render_cluster.worker.backends.blender import BlenderBackend

        return BlenderBackend(**kwargs)
    if name == "tpu-raytrace":
        from tpu_render_cluster.worker.backends.tpu_raytrace import TpuRaytraceBackend

        return TpuRaytraceBackend(**kwargs)
    if name == "mock":
        from tpu_render_cluster.worker.backends.mock import MockBackend

        return MockBackend(**kwargs)
    raise ValueError(f"Unknown render backend: {name!r}")


__all__ = ["RenderBackend", "create_backend"]
