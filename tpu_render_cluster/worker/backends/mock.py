"""Sleep-based fake renderer for integration tests.

Fills the role of the in-process fake worker recommended by SURVEY.md §4:
exercising strategies, steal races, reconnects, and trace collection with
zero Blender and zero TPU.
"""

from __future__ import annotations

import asyncio
import time
from typing import Callable

from tpu_render_cluster.jobs.models import BlenderJob
from tpu_render_cluster.traces.worker_trace import FrameRenderTime
from tpu_render_cluster.worker.backends.base import RenderBackend


class MockBackend(RenderBackend):
    def __init__(
        self,
        *,
        load_seconds: float = 0.005,
        render_seconds: float = 0.02,
        save_seconds: float = 0.005,
        fail_frames: set[int] | None = None,
        render_seconds_fn: Callable[[int], float] | None = None,
    ) -> None:
        self.load_seconds = load_seconds
        self.render_seconds = render_seconds
        self.save_seconds = save_seconds
        self.fail_frames = fail_frames or set()
        # Per-frame render duration override, for heterogeneous-cost
        # workloads (animated scenes whose cost varies by frame index).
        self.render_seconds_fn = render_seconds_fn
        self.rendered_frames: list[int] = []
        # (frame_index, tile) pairs, recorded only for tiled renders.
        self.rendered_units: list[tuple[int, int | None]] = []

    async def render_frame(
        self, job: BlenderJob, frame_index: int, tile: int | None = None
    ) -> FrameRenderTime:
        started_process = time.time()
        await asyncio.sleep(self.load_seconds)
        finished_loading = time.time()
        if frame_index in self.fail_frames:
            self.fail_frames.discard(frame_index)  # fail once, then succeed
            raise RuntimeError(f"mock render failure for frame {frame_index}")
        started_rendering = time.time()
        render_seconds = (
            self.render_seconds_fn(frame_index)
            if self.render_seconds_fn is not None
            else self.render_seconds
        )
        await asyncio.sleep(render_seconds)
        finished_rendering = time.time()
        self.rendered_units.append((frame_index, tile))
        saving_started = time.time()
        await asyncio.sleep(self.save_seconds)
        saving_finished = time.time()
        self.rendered_frames.append(frame_index)
        return FrameRenderTime(
            started_process_at=started_process,
            finished_loading_at=finished_loading,
            started_rendering_at=started_rendering,
            finished_rendering_at=finished_rendering,
            file_saving_started_at=saving_started,
            file_saving_finished_at=saving_finished,
            exited_process_at=time.time(),
        )
