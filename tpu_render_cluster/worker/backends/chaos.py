"""Fault-aware backend wrapper (the chaos engine's render-layer hook).

``FaultyBackend`` delegates every frame to an inner backend (normally the
sleep-based mock) and consults a ``WorkerChaosController``
(chaos/inject.py) at the three points where worker faults bite:

- **before the render** — ``crash_before_result`` kills the worker here,
  so the frame's work is lost and the master must re-render it elsewhere;
  ``hang`` parks the backend forever, leaving heartbeats to discover the
  wedge and evict;
- **around the render** — ``slow_render`` stretches the measured duration
  by the plan's multiplier (a straggler);
- **after the render** — ``crash_after_result`` arms a kill that fires the
  moment this frame's finished event clears the socket, so the result
  survives but the worker doesn't.

With a fault-free controller the wrapper is pass-through; production
backends never import this module.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import replace
from typing import TYPE_CHECKING

from tpu_render_cluster.jobs.models import BlenderJob
from tpu_render_cluster.traces.worker_trace import FrameRenderTime
from tpu_render_cluster.worker.backends.base import RenderBackend

if TYPE_CHECKING:
    from tpu_render_cluster.chaos.inject import WorkerChaosController


class FaultyBackend(RenderBackend):
    """Wraps a real backend with plan-driven render faults."""

    def __init__(self, inner: RenderBackend, controller: "WorkerChaosController") -> None:
        self._inner = inner
        self._controller = controller
        self._ordinal = 0

    async def render_frame(
        self, job: BlenderJob, frame_index: int, tile: int | None = None
    ) -> FrameRenderTime:
        self._ordinal += 1
        ordinal = self._ordinal
        controller = self._controller
        # A crash_before_result trigger cancels the worker task here; the
        # cancellation lands at the next await point below.
        controller.note_render_start(frame_index, ordinal)
        if controller.should_hang(ordinal):
            await asyncio.Event().wait()  # parked until the run tears down
        started = time.perf_counter()
        timing = await self._inner.render_frame(job, frame_index, tile=tile)
        multiplier = controller.render_multiplier()
        if multiplier > 1.0:
            # Stretch the frame's wall time by the straggler factor; only
            # the exit timestamp moves, preserving the 7-point monotonic
            # ordering the performance reducer requires.
            await asyncio.sleep((time.perf_counter() - started) * (multiplier - 1.0))
            timing = replace(timing, exited_process_at=time.time())
        controller.note_render_done(frame_index, ordinal)
        return timing
