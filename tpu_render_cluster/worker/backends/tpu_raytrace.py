"""The `tpu-raytrace` render backend: pure-JAX path tracing on TPU.

Drop-in replacement for the Blender subprocess backend behind the same
``RenderBackend`` interface — it emits the identical 7-phase
``FrameRenderTime`` so traces and the analysis suite cannot tell the
backends apart (BASELINE.md north star). Phase mapping:

- started_process/finished_loading: scene + camera build (host->device);
- started/finished_rendering: device compute (block_until_ready fenced);
- file_saving: tonemap + PNG/JPEG encode + write;
- exited_process: after the output file hits disk.

The heavy work runs in a thread (`asyncio.to_thread`) so heartbeats and
queue RPCs stay responsive while a frame renders.
"""

from __future__ import annotations

import asyncio
import time
from pathlib import Path

from tpu_render_cluster.jobs.models import BlenderJob
from tpu_render_cluster.traces.worker_trace import FrameRenderTime
from tpu_render_cluster.utils.paths import parse_with_base_directory_prefix
from tpu_render_cluster.worker.backends.base import RenderBackend


class TpuRaytraceBackend(RenderBackend):
    def __init__(
        self,
        *,
        base_directory: str | Path | None = None,
        width: int = 512,
        height: int = 512,
        samples: int = 8,
        max_bounces: int = 4,
        tile_size: int | None = None,
        sharding: str | None = None,
        wavefront: str | None = None,
    ) -> None:
        self.base_directory = Path(base_directory) if base_directory else None
        self.width = width
        self.height = height
        self.samples = samples
        self.max_bounces = max_bounces
        self.tile_size = tile_size
        # None = single device; "tile" / "spp" shard across the local mesh
        # (tpu_render_cluster/parallel/sharded_render.py).
        self.sharding = sharding
        # Wavefront (compact + bucketed relaunch) execution: None defers
        # to the TRC_WAVEFRONT env tier; "off"/"auto"/"force" override it
        # per backend (render/compaction.py). Only applies to the
        # single-device path — tile/spp sharding gets the IN-JIT
        # compaction (live-count tail skip) instead, which composes with
        # shard_map.
        self.wavefront = wavefront

    def _use_wavefront(self, scene_name: str) -> bool:
        if self.sharding in ("tile", "spp"):
            return False
        from tpu_render_cluster.render.compaction import wavefront_active

        return wavefront_active(scene_name, backend_flag=self.wavefront)

    def warm(self, scene_name: str) -> None:
        """Compile + execute the renderer once, outside any job window.

        The process-level analog of pre-pulling the Blender container
        (reference: pull-blender-image.sh): the first XLA compile costs
        20-40 s and must not land inside a rendered frame's trace.
        """
        import numpy as np

        from tpu_render_cluster.render.scene import scene_for_job_name

        # Accept job names as well as scene names, resolving exactly like
        # the render path does — otherwise the warmed program can differ
        # from the one the job compiles.
        scene_name = scene_for_job_name(scene_name)

        if self.sharding in ("tile", "spp"):
            from tpu_render_cluster.parallel.sharded_render import render_frame_sharded

            np.asarray(
                render_frame_sharded(
                    scene_name,
                    1,
                    width=self.width,
                    height=self.height,
                    samples=self.samples,
                    max_bounces=self.max_bounces,
                    mode=self.sharding,
                )
            )
        elif self._use_wavefront(scene_name):
            # One full wavefront frame: compiles the compaction +
            # bounce programs for the buckets this workload actually
            # visits (render_compiles_total then stays flat over the
            # job's frames).
            from tpu_render_cluster.render.compaction import render_frame_wavefront

            np.asarray(
                render_frame_wavefront(
                    scene_name,
                    1,
                    width=self.width,
                    height=self.height,
                    samples=self.samples,
                    max_bounces=self.max_bounces,
                )
            )
        else:
            from tpu_render_cluster.render.integrator import fused_frame_renderer

            np.asarray(
                fused_frame_renderer(
                    scene_name,
                    self.width,
                    self.height,
                    self.samples,
                    self.max_bounces,
                )(1)
            )

    async def render_frame(self, job: BlenderJob, frame_index: int) -> FrameRenderTime:
        return await asyncio.to_thread(self._render_sync, job, frame_index)

    @staticmethod
    def _observe_render_obs(*, compile_seconds: float, execute_seconds: float) -> None:
        """Feed the process-global obs registry (one TPU per process).

        ``render_compile_seconds`` is the loading phase (fetching — or
        first building — the compiled renderer); ``render_execute_seconds``
        is fenced device compute + readback. The frames/s gauge uses the
        same device-time accounting bench.py reports (frames per second of
        synced device execution), so the live gauge and the headline bench
        number are directly comparable.
        """
        from tpu_render_cluster.obs import get_registry, render_fps_gauge

        registry = get_registry()
        registry.histogram(
            "render_compile_seconds",
            "Per-frame compiled-renderer fetch/build (the 'loading' phase)",
        ).observe(max(0.0, compile_seconds))
        registry.histogram(
            "render_execute_seconds",
            "Per-frame device render + readback (block-until-ready fenced)",
        ).observe(max(0.0, execute_seconds))
        if execute_seconds > 0:
            render_fps_gauge(registry).set(1.0 / execute_seconds)

    def _render_sync(self, job: BlenderJob, frame_index: int) -> FrameRenderTime:
        import numpy as np

        from tpu_render_cluster.render.image_io import output_path_for_frame, write_image
        from tpu_render_cluster.render.integrator import fused_frame_renderer, tonemap
        from tpu_render_cluster.render.scene import scene_for_job_name

        started_process_at = time.time()

        scene_name = scene_for_job_name(job.job_name)
        # "Loading" = fetching (or first-building) the compiled renderer for
        # this scene/config — the analog of Blender's .blend load phase.
        # Scene construction itself is fused into the XLA program: one
        # device dispatch per frame instead of dozens of eager array ops
        # (which cost ~2 s/frame over a tunneled device).
        # Wavefront mode has no single cached renderer (its per-bucket
        # programs compile lazily inside the render — warm() pre-visits
        # them), so its loading phase is just scene-name resolution.
        use_wavefront = self._use_wavefront(scene_name)
        if self.sharding not in ("tile", "spp") and not use_wavefront:
            renderer = fused_frame_renderer(
                scene_name,
                self.width,
                self.height,
                self.samples,
                self.max_bounces,
            )
        finished_loading_at = time.time()

        started_rendering_at = time.time()
        if self.sharding in ("tile", "spp"):
            from tpu_render_cluster.parallel.sharded_render import render_frame_sharded

            linear = render_frame_sharded(
                scene_name,
                frame_index,
                width=self.width,
                height=self.height,
                samples=self.samples,
                max_bounces=self.max_bounces,
                mode=self.sharding,
            )
            display = tonemap(linear)
        elif use_wavefront:
            from tpu_render_cluster.render.compaction import render_frame_wavefront

            linear = render_frame_wavefront(
                scene_name,
                frame_index,
                width=self.width,
                height=self.height,
                samples=self.samples,
                max_bounces=self.max_bounces,
            )
            display = tonemap(linear)
        else:
            display = renderer(frame_index)
        # One device sync per frame: np.asarray blocks on completion AND
        # reads the image back (a separate block_until_ready would pay a
        # second round-trip on tunneled devices). Readback counts as
        # rendering, like Blender's in-process compositing; "saving" below
        # is encode + disk only.
        pixels = np.asarray(display)
        finished_rendering_at = time.time()

        file_saving_started_at = time.time()
        output_directory = parse_with_base_directory_prefix(
            job.output_directory_path, self.base_directory
        )
        path = output_path_for_frame(
            output_directory,
            job.output_file_name_format,
            job.output_file_format,
            frame_index,
        )
        write_image(path, pixels, job.output_file_format)
        file_saving_finished_at = time.time()

        self._observe_render_obs(
            compile_seconds=finished_loading_at - started_process_at,
            execute_seconds=finished_rendering_at - started_rendering_at,
        )
        return FrameRenderTime(
            started_process_at=started_process_at,
            finished_loading_at=finished_loading_at,
            started_rendering_at=started_rendering_at,
            finished_rendering_at=finished_rendering_at,
            file_saving_started_at=file_saving_started_at,
            file_saving_finished_at=file_saving_finished_at,
            exited_process_at=time.time(),
        )
