"""The `tpu-raytrace` render backend: pure-JAX path tracing on TPU.

Drop-in replacement for the Blender subprocess backend behind the same
``RenderBackend`` interface — it emits the identical 7-phase
``FrameRenderTime`` so traces and the analysis suite cannot tell the
backends apart (BASELINE.md north star). Phase mapping:

- started_process/finished_loading: scene + camera build (host->device);
- started/finished_rendering: device compute (block_until_ready fenced);
- file_saving: tonemap + PNG/JPEG encode + write;
- exited_process: after the output file hits disk.

The heavy work runs in a thread (`asyncio.to_thread`) so heartbeats and
queue RPCs stay responsive while a frame renders.
"""

from __future__ import annotations

import asyncio
import time
from pathlib import Path

from tpu_render_cluster.jobs.models import BlenderJob
from tpu_render_cluster.traces.worker_trace import FrameRenderTime
from tpu_render_cluster.utils.paths import parse_with_base_directory_prefix
from tpu_render_cluster.worker.backends.base import RenderBackend


class TpuRaytraceBackend(RenderBackend):
    def __init__(
        self,
        *,
        base_directory: str | Path | None = None,
        width: int = 512,
        height: int = 512,
        samples: int = 8,
        max_bounces: int = 4,
        tile_size: int | None = None,
        sharding: str | None = None,
    ) -> None:
        self.base_directory = Path(base_directory) if base_directory else None
        self.width = width
        self.height = height
        self.samples = samples
        self.max_bounces = max_bounces
        self.tile_size = tile_size
        # None = single device; "tile" / "spp" shard across the local mesh
        # (tpu_render_cluster/parallel/sharded_render.py).
        self.sharding = sharding

    async def render_frame(self, job: BlenderJob, frame_index: int) -> FrameRenderTime:
        return await asyncio.to_thread(self._render_sync, job, frame_index)

    def _render_sync(self, job: BlenderJob, frame_index: int) -> FrameRenderTime:
        import jax.numpy as jnp
        import numpy as np

        from tpu_render_cluster.render.camera import scene_camera
        from tpu_render_cluster.render.image_io import output_path_for_frame, write_image
        from tpu_render_cluster.render.integrator import render_frame, tonemap
        from tpu_render_cluster.render.scene import build_scene, scene_for_job_name

        started_process_at = time.time()

        scene_name = scene_for_job_name(job.job_name)
        # Build scene/camera eagerly so "loading" is observable, mirroring
        # Blender's .blend load phase.
        scene = build_scene(scene_name, frame_index)
        camera = scene_camera(scene_name, frame_index)
        for leaf in (*scene, *camera):
            leaf.block_until_ready()
        finished_loading_at = time.time()

        started_rendering_at = time.time()
        if self.sharding in ("tile", "spp"):
            from tpu_render_cluster.parallel.sharded_render import render_frame_sharded

            linear = render_frame_sharded(
                scene_name,
                frame_index,
                width=self.width,
                height=self.height,
                samples=self.samples,
                max_bounces=self.max_bounces,
                mode=self.sharding,
            )
        else:
            linear = render_frame(
                scene_name,
                frame_index,
                width=self.width,
                height=self.height,
                samples=self.samples,
                max_bounces=self.max_bounces,
                tile_size=self.tile_size,
            )
        linear.block_until_ready()
        finished_rendering_at = time.time()

        file_saving_started_at = time.time()
        pixels = np.asarray(tonemap(linear))
        output_directory = parse_with_base_directory_prefix(
            job.output_directory_path, self.base_directory
        )
        path = output_path_for_frame(
            output_directory,
            job.output_file_name_format,
            job.output_file_format,
            frame_index,
        )
        write_image(path, pixels, job.output_file_format)
        file_saving_finished_at = time.time()

        return FrameRenderTime(
            started_process_at=started_process_at,
            finished_loading_at=finished_loading_at,
            started_rendering_at=started_rendering_at,
            finished_rendering_at=finished_rendering_at,
            file_saving_started_at=file_saving_started_at,
            file_saving_finished_at=file_saving_finished_at,
            exited_process_at=time.time(),
        )
