"""The `tpu-raytrace` render backend: pure-JAX path tracing on TPU.

Drop-in replacement for the Blender subprocess backend behind the same
``RenderBackend`` interface — it emits the identical 7-phase
``FrameRenderTime`` so traces and the analysis suite cannot tell the
backends apart (BASELINE.md north star). Phase mapping:

- started_process/finished_loading: scene + camera build (host->device);
- started/finished_rendering: device compute (block_until_ready fenced);
- file_saving: tonemap + PNG/JPEG encode + write;
- exited_process: after the output file hits disk.

The heavy work runs in a thread (`asyncio.to_thread`) so heartbeats and
queue RPCs stay responsive while a frame renders.
"""

from __future__ import annotations

import asyncio
import time
from pathlib import Path

from tpu_render_cluster.jobs.models import BlenderJob
from tpu_render_cluster.traces.worker_trace import FrameRenderTime
from tpu_render_cluster.utils.paths import parse_with_base_directory_prefix
from tpu_render_cluster.worker.backends.base import RenderBackend


class TpuRaytraceBackend(RenderBackend):
    def __init__(
        self,
        *,
        base_directory: str | Path | None = None,
        width: int = 512,
        height: int = 512,
        samples: int = 8,
        max_bounces: int = 4,
        tile_size: int | None = None,
        sharding: str | None = None,
        wavefront: str | None = None,
        raypool: str | None = None,
    ) -> None:
        self.base_directory = Path(base_directory) if base_directory else None
        self.width = width
        self.height = height
        self.samples = samples
        self.max_bounces = max_bounces
        self.tile_size = tile_size
        # None = single device; "tile" / "spp" shard across the local mesh
        # (tpu_render_cluster/parallel/sharded_render.py).
        self.sharding = sharding
        # Wavefront (compact + bucketed relaunch) execution: None defers
        # to the TRC_WAVEFRONT env tier; "off"/"auto"/"force" override it
        # per backend (render/compaction.py). Only applies to the
        # single-device path — tile/spp sharding gets the IN-JIT
        # compaction (live-count tail skip) instead, which composes with
        # shard_map.
        self.wavefront = wavefront
        # Device-resident ray pool (render/raypool.py): None defers to the
        # TRC_RAYPOOL env tier; "off"/"auto"/"force" override per backend.
        # Auto fires for multi-frame deep-walk jobs — the queue's
        # note_upcoming_frames hint supplies the work-ahead — and the
        # backend then renders several of ITS OWN queued frames in one
        # pool batch, serving later requests from the cache below.
        # Worker-internal only: one frame per request on the wire.
        self.raypool = raypool
        # Work units (jobs.tiles.WorkUnit) of each job still queued here.
        self._upcoming: dict[str, tuple] = {}
        # (job_name, frame_index, tile) -> linear image rendered ahead by
        # a pool batch. Bounded BY BYTES: stale entries (stolen/removed
        # units we rendered ahead of) are evicted oldest-first.
        self._raypool_cache: dict[tuple[str, int, int | None], object] = {}

    # Staleness backstop, not a working-set budget: live entries drain
    # within one pool window of requests, so anything pushing the cache
    # past this is stolen/removed frames.
    _RAYPOOL_CACHE_MAX_BYTES = 64 * 1024 * 1024

    def note_upcoming_frames(self, job: BlenderJob, units: tuple) -> None:
        """Queue hint (RenderBackend hint protocol): same-job work units
        still queued on this worker, i.e. what a pool batch may render
        ahead (same-tile units of other frames, for tiled jobs).

        An empty hint drops the job's entry — the map tracks only jobs
        with outstanding local work, so a long-lived worker's job history
        doesn't accumulate here. Bare ints are accepted as whole-frame
        units (the pre-tiling call shape).
        """
        if units:
            from tpu_render_cluster.jobs.tiles import WorkUnit

            self._upcoming[job.job_name] = tuple(
                WorkUnit(u) if isinstance(u, int) else u for u in units
            )
        else:
            self._upcoming.pop(job.job_name, None)

    def _use_wavefront(self, scene_name: str) -> bool:
        if self.sharding in ("tile", "spp"):
            return False
        from tpu_render_cluster.render.compaction import wavefront_active

        return wavefront_active(scene_name, backend_flag=self.wavefront)

    def _use_raypool(self, scene_name: str, frames_ahead: int) -> bool:
        if self.sharding in ("tile", "spp"):
            return False
        from tpu_render_cluster.render.raypool import raypool_active

        return raypool_active(
            scene_name,
            backend_flag=self.raypool,
            frames_ahead=frames_ahead,
        )

    def warm(self, scene_name: str) -> None:
        """Compile + execute the renderer once, outside any job window.

        The process-level analog of pre-pulling the Blender container
        (reference: pull-blender-image.sh): the first XLA compile costs
        20-40 s and must not land inside a rendered frame's trace.
        """
        import numpy as np

        from tpu_render_cluster.render.scene import scene_for_job_name

        # Accept job names as well as scene names, resolving exactly like
        # the render path does — otherwise the warmed program can differ
        # from the one the job compiles.
        scene_name = scene_for_job_name(scene_name)

        if self.sharding in ("tile", "spp"):
            from tpu_render_cluster.parallel.sharded_render import render_frame_sharded

            np.asarray(
                render_frame_sharded(
                    scene_name,
                    1,
                    width=self.width,
                    height=self.height,
                    samples=self.samples,
                    max_bounces=self.max_bounces,
                    mode=self.sharding,
                )
            )
            return
        if self._use_raypool(scene_name, frames_ahead=1):
            # The pool program is one compile per pool config, batch size
            # independent — a single-frame batch warms it completely. The
            # per-frame fallback below is ALSO warmed: the job's tail
            # frame (nothing queued behind it) renders through it, and
            # its compile must not land inside a frame trace either.
            from tpu_render_cluster.render.raypool import render_batch_raypool

            np.asarray(
                render_batch_raypool(
                    scene_name,
                    [1],
                    width=self.width,
                    height=self.height,
                    samples=self.samples,
                    max_bounces=self.max_bounces,
                )[0]
            )
        if self._use_wavefront(scene_name):
            # One full wavefront frame: compiles the compaction +
            # bounce programs for the buckets this workload actually
            # visits (render_compiles_total then stays flat over the
            # job's frames).
            from tpu_render_cluster.render.compaction import render_frame_wavefront

            np.asarray(
                render_frame_wavefront(
                    scene_name,
                    1,
                    width=self.width,
                    height=self.height,
                    samples=self.samples,
                    max_bounces=self.max_bounces,
                )
            )
        else:
            from tpu_render_cluster.render.integrator import fused_frame_renderer

            np.asarray(
                fused_frame_renderer(
                    scene_name,
                    self.width,
                    self.height,
                    self.samples,
                    self.max_bounces,
                )(1)
            )

    async def render_frame(
        self, job: BlenderJob, frame_index: int, tile: int | None = None
    ) -> FrameRenderTime:
        return await asyncio.to_thread(self._render_sync, job, frame_index, tile)

    def _trim_raypool_cache(self) -> None:
        """Evict oldest rendered-ahead frames past the byte cap (stale
        entries accumulate when frames we batched ahead get stolen or
        removed; at production resolution each image is megabytes, so the
        bound must be bytes, not entries)."""
        excess = (
            sum(
                getattr(image, "nbytes", 0)
                for image in self._raypool_cache.values()
            )
            - self._RAYPOOL_CACHE_MAX_BYTES
        )
        while self._raypool_cache and excess > 0:
            victim = self._raypool_cache.pop(next(iter(self._raypool_cache)))
            excess -= getattr(victim, "nbytes", 0)

    @staticmethod
    def _observe_render_obs(
        *, compile_seconds: float, execute_seconds: float,
        from_cache: bool = False, kernel: str | None = None,
    ) -> None:
        """Feed the process-global obs registry (one TPU per process).

        ``render_compile_seconds`` is the loading phase (fetching — or
        first building — the compiled renderer); ``render_execute_seconds``
        is fenced device compute + readback. The frames/s gauge uses the
        same device-time accounting bench.py reports (frames per second of
        synced device execution), so the live gauge and the headline bench
        number are directly comparable.
        """
        from tpu_render_cluster.obs import get_registry, render_fps_gauge

        registry = get_registry()
        registry.histogram(
            "render_compile_seconds",
            "Per-frame compiled-renderer fetch/build (the 'loading' phase)",
        ).observe(max(0.0, compile_seconds))
        if from_cache:
            # A ray-pool cache hit: this frame's device time was amortized
            # into the batch that rendered it ahead — its ~tonemap-only
            # execute time belongs in neither the per-frame execute
            # histogram nor the fps gauge (both would report fantasy
            # per-frame device rates under batching).
            registry.counter(
                "render_raypool_cache_hits_total",
                "Frames served from the ray-pool rendered-ahead cache",
            ).inc()
            return
        registry.histogram(
            "render_execute_seconds",
            "Per-frame device render + readback (block-until-ready fenced)",
        ).observe(max(0.0, execute_seconds))
        if execute_seconds > 0:
            render_fps_gauge(registry).set(1.0 / execute_seconds)
        if kernel is not None and execute_seconds > 0:
            # Roofline pairing: this tier's whole frame is one fenced
            # program execution (render + readback), keyed identically to
            # the cost capture inside the renderer factory.
            from tpu_render_cluster.obs.profiling import get_profiler

            get_profiler().record_execute(kernel, execute_seconds)

    def _render_sync(
        self, job: BlenderJob, frame_index: int, tile: int | None = None
    ) -> FrameRenderTime:
        import numpy as np

        from tpu_render_cluster.render.image_io import (
            output_path_for_frame,
            output_path_for_tile,
            write_image,
        )
        from tpu_render_cluster.render.integrator import fused_frame_renderer, tonemap
        from tpu_render_cluster.render.scene import scene_for_job_name

        started_process_at = time.time()

        scene_name = scene_for_job_name(job.job_name)
        # Tiled work unit: resolve the tile's pixel region once. All three
        # execution tiers below serve it through their region paths, which
        # trace the FULL frame's rays/RNG restricted to these pixels — a
        # master-assembled grid of tiles is pixel-identical to the
        # whole-frame render (render/integrator.region_rays_and_seed).
        region = None
        if tile is not None:
            from tpu_render_cluster.jobs.tiles import tile_bounds

            if job.tile_grid is None:
                raise RuntimeError(
                    f"Tile {tile} requested but job {job.job_name!r} "
                    "carries no tile grid."
                )
            region = tile_bounds(
                tile, job.tile_grid, width=self.width, height=self.height
            )
        # "Loading" = fetching (or first-building) the compiled renderer for
        # this scene/config — the analog of Blender's .blend load phase.
        # Scene construction itself is fused into the XLA program: one
        # device dispatch per frame instead of dozens of eager array ops
        # (which cost ~2 s/frame over a tunneled device).
        # Wavefront mode has no single cached renderer (its per-bucket
        # programs compile lazily inside the render — warm() pre-visits
        # them), so its loading phase is just scene-name resolution; same
        # for the ray-pool path (one pool program per config, warmed).
        cache_key = (job.job_name, frame_index, tile)
        cached_linear = self._raypool_cache.pop(cache_key, None)
        # Work-ahead for a pool batch: same-job units still queued HERE
        # with the SAME tile (a pool batch spans frames, not regions).
        upcoming = [
            u.frame_index
            for u in self._upcoming.get(job.job_name, ())
            if u.tile == tile
            and u.frame_index != frame_index
            and (job.job_name, u.frame_index, tile) not in self._raypool_cache
        ]
        use_raypool = cached_linear is None and self._use_raypool(
            scene_name, frames_ahead=len(upcoming)
        )
        use_wavefront = (
            cached_linear is None
            and not use_raypool
            and self._use_wavefront(scene_name)
        )
        use_sharded = self.sharding in ("tile", "spp") and region is None
        if (
            not use_sharded
            and cached_linear is None
            and not use_wavefront
            and not use_raypool
            and region is None
        ):
            renderer = fused_frame_renderer(
                scene_name,
                self.width,
                self.height,
                self.samples,
                self.max_bounces,
            )
        finished_loading_at = time.time()

        started_rendering_at = time.time()
        if cached_linear is not None:
            # Rendered ahead by an earlier pool batch of this job: only
            # the tonemap + readback run now. The batch's device time was
            # carried by the frame that triggered it — per-frame phase
            # timings under batching reflect that amortization.
            display = tonemap(cached_linear)
        elif use_sharded:
            from tpu_render_cluster.parallel.sharded_render import render_frame_sharded

            linear = render_frame_sharded(
                scene_name,
                frame_index,
                width=self.width,
                height=self.height,
                samples=self.samples,
                max_bounces=self.max_bounces,
                mode=self.sharding,
            )
            display = tonemap(linear)
        elif use_raypool:
            from tpu_render_cluster.render.raypool import (
                raypool_frame_cap,
                render_batch_raypool,
            )

            # One pool window: this unit plus the next queued same-tile
            # frames of the same job (the queue's hint — all assigned to
            # THIS worker, so nothing is rendered speculatively). Units
            # rendered ahead are served from the cache on their own
            # requests.
            batch = [frame_index] + upcoming[: raypool_frame_cap() - 1]
            images = render_batch_raypool(
                scene_name,
                batch,
                width=self.width,
                height=self.height,
                samples=self.samples,
                max_bounces=self.max_bounces,
                region=region,
            )
            for ahead_frame, image in zip(batch[1:], images[1:]):
                self._raypool_cache[(job.job_name, ahead_frame, tile)] = image
            self._trim_raypool_cache()
            display = tonemap(images[0])
        elif use_wavefront:
            from tpu_render_cluster.render.compaction import (
                render_frame_wavefront,
                render_region_wavefront,
            )

            if region is None:
                linear = render_frame_wavefront(
                    scene_name,
                    frame_index,
                    width=self.width,
                    height=self.height,
                    samples=self.samples,
                    max_bounces=self.max_bounces,
                )
            else:
                y0, x0, tile_height, tile_width = region
                linear = render_region_wavefront(
                    scene_name,
                    frame_index,
                    y0=y0,
                    x0=x0,
                    tile_height=tile_height,
                    tile_width=tile_width,
                    width=self.width,
                    height=self.height,
                    samples=self.samples,
                    max_bounces=self.max_bounces,
                )
            display = tonemap(linear)
        elif region is not None:
            # Masked tier, one tile: the jitted region program (one
            # compile per tile shape; y0/x0/frame are traced). Local
            # tile/spp sharding is bypassed for cluster-tile units — the
            # unit is already sub-frame work.
            from tpu_render_cluster.render.integrator import render_frame_region

            y0, x0, tile_height, tile_width = region
            linear = render_frame_region(
                scene_name,
                frame_index,
                y0=y0,
                x0=x0,
                tile_height=tile_height,
                tile_width=tile_width,
                width=self.width,
                height=self.height,
                samples=self.samples,
                max_bounces=self.max_bounces,
            )
            display = tonemap(linear)
        else:
            display = renderer(frame_index)
        # One device sync per frame: np.asarray blocks on completion AND
        # reads the image back (a separate block_until_ready would pay a
        # second round-trip on tunneled devices). Readback counts as
        # rendering, like Blender's in-process compositing; "saving" below
        # is encode + disk only.
        pixels = np.asarray(display)
        finished_rendering_at = time.time()

        file_saving_started_at = time.time()
        output_directory = parse_with_base_directory_prefix(
            job.output_directory_path, self.base_directory
        )
        if tile is None:
            path = output_path_for_frame(
                output_directory,
                job.output_file_name_format,
                job.output_file_format,
                frame_index,
            )
        else:
            # One tile file per unit; the master's assembly service
            # stitches the grid into the frame file and removes these.
            # Always PNG (lossless — see image_io.output_path_for_tile);
            # the assembler encodes the final frame in the job's format.
            path = output_path_for_tile(
                output_directory,
                job.output_file_name_format,
                job.output_file_format,
                frame_index,
                tile,
                job.tile_grid,
            )
        write_image(
            path, pixels, "PNG" if tile is not None else job.output_file_format
        )
        file_saving_finished_at = time.time()

        # Which roofline kernel this frame's fenced execute time pairs
        # with: only tiers whose frame is ONE program execution keyed by
        # a factory-side cost capture (the wavefront/raypool drivers pair
        # their own launches internally; cache hits executed nothing).
        kernel = None
        if cached_linear is None and not use_raypool and not use_wavefront:
            from tpu_render_cluster.obs.profiling import kernel_key

            if use_sharded:
                pass  # sharded programs are not cost-captured (per-device)
            elif region is not None:
                y0, x0, tile_height, tile_width = region
                kernel = kernel_key(
                    "region", scene_name,
                    w=self.width, h=self.height,
                    th=tile_height, tw=tile_width,
                    s=self.samples, b=self.max_bounces,
                )
            else:
                kernel = kernel_key(
                    "masked", scene_name,
                    w=self.width, h=self.height,
                    s=self.samples, b=self.max_bounces,
                )
        self._observe_render_obs(
            compile_seconds=finished_loading_at - started_process_at,
            execute_seconds=finished_rendering_at - started_rendering_at,
            from_cache=cached_linear is not None,
            kernel=kernel,
        )
        return FrameRenderTime(
            started_process_at=started_process_at,
            finished_loading_at=finished_loading_at,
            started_rendering_at=started_rendering_at,
            finished_rendering_at=finished_rendering_at,
            file_saving_started_at=file_saving_started_at,
            file_saving_finished_at=file_saving_finished_at,
            exited_process_at=time.time(),
        )
