from tpu_render_cluster.worker.runtime import Worker

__all__ = ["Worker"]
