"""Worker runtime: reconnecting client + heartbeat responder + message manager.

Reference: worker/src/connection/mod.rs:46-713. The worker connects with
exponential backoff, performs the 3-step handshake (first-connection, or
reconnecting after socket death), then runs three loops until the job
finishes: the heartbeat responder (tracing every 8th ping —
``TRACE_EVERY_NTH_PING`` at worker/src/connection/mod.rs:46), the message
manager (queue add/remove, job started/finished), and the automatic render
queue.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Callable

from tpu_render_cluster import PROTOCOL_VERSION
from tpu_render_cluster.obs import (
    LoopLagMonitor,
    MetricsRegistry,
    Tracer,
    get_registry,
)
from tpu_render_cluster.protocol import messages as pm
from tpu_render_cluster.traces.worker_trace import WorkerTrace, WorkerTraceBuilder
from tpu_render_cluster.transport.actors import MessageRouter, SenderHandle
from tpu_render_cluster.transport.reconnect import (
    ReconnectingClient,
    TransportMetrics,
    connect_with_exponential_backoff,
)
from tpu_render_cluster.transport.ws import WebSocketClosed, WebSocketConnection
from tpu_render_cluster.transport.wirecost import WireAccounting
from tpu_render_cluster.utils.cancellation import CancellationToken
from tpu_render_cluster.worker.backends.base import RenderBackend
from tpu_render_cluster.worker.queue import WorkerAutomaticQueue

logger = logging.getLogger(__name__)

TRACE_EVERY_NTH_PING = 8  # reference: worker/src/connection/mod.rs:46
HANDSHAKE_TIMEOUT = 30.0


class ReconnectRefused(WebSocketClosed):
    """The master refused a RECONNECTING handshake (it does not know this
    worker — typically a restarted master whose in-memory registry died).
    The caller retries with a fresh first-connection announce instead of
    replaying stale session state into a master that never saw it."""


async def _perform_handshake(
    ws: WebSocketConnection,
    worker_id: int,
    *,
    is_reconnect: bool,
    last_epoch: int | None = None,
    wire: WireAccounting | None = None,
) -> tuple[int | None, bool]:
    """Client side of the 3-step handshake; returns ``(epoch, fresh)``.

    Reference: worker/src/connection/mod.rs:402-454, extended with epoch
    fencing (PROTOCOL.md §Epoch fencing & failover): the master's
    handshake request optionally carries its ledger epoch. A reconnecting
    worker that sees a DIFFERENT epoch than the master it lost is talking
    to a new incarnation — it announces ``first-connection`` (a fresh
    session) instead of ``reconnecting``, because the new master has no
    session to resume. ``fresh`` is True when a first-connection announce
    was sent.
    """
    if wire is None:
        wire = WireAccounting(None)  # bare-codec passthrough
    request = wire.decode(await ws.receive_text())
    if not isinstance(request, pm.MasterHandshakeRequest):
        raise WebSocketClosed(f"Expected handshake request, got {type(request)}")
    announce_fresh = not is_reconnect or request.epoch != last_epoch
    if is_reconnect and announce_fresh:
        logger.info(
            "Master epoch changed (%s -> %s); re-announcing as a fresh session.",
            last_epoch,
            request.epoch,
        )
    handshake_type = (
        pm.HANDSHAKE_TYPE_FIRST_CONNECTION
        if announce_fresh
        else pm.HANDSHAKE_TYPE_RECONNECTING
    )
    await ws.send_text(
        wire.encode(
            pm.WorkerHandshakeResponse(handshake_type, PROTOCOL_VERSION, worker_id)
        )
    )
    ack = wire.decode(await ws.receive_text())
    if not isinstance(ack, pm.MasterHandshakeAcknowledgement) or not ack.ok:
        if handshake_type == pm.HANDSHAKE_TYPE_RECONNECTING:
            # An epoch-less restarted master refuses reconnects from
            # workers it never met; fall back to a fresh announce on the
            # next attempt (the master aborts this socket after refusing).
            raise ReconnectRefused("Master refused the reconnect handshake.")
        raise WebSocketClosed("Master refused the handshake.")
    return request.epoch, announce_fresh


class Worker:
    """A single render node."""

    def __init__(
        self,
        master_host: str,
        master_port: int,
        backend: RenderBackend,
        *,
        tracer: WorkerTraceBuilder | None = None,
        metrics: MetricsRegistry | None = None,
        span_tracer: Tracer | None = None,
        connection_wrapper: Callable[[WebSocketConnection], WebSocketConnection]
        | None = None,
    ) -> None:
        self.master_host = master_host
        self.master_port = master_port
        self.backend = backend
        self.worker_id = pm.generate_worker_id()
        self.tracer = tracer or WorkerTraceBuilder()
        # Live observability: the worker's registry ships to the master as
        # the heartbeat's compact payload; the span tracer is one Perfetto
        # process row per worker. The registry defaults to the
        # PROCESS-GLOBAL one so process-scoped sources (the tpu-raytrace
        # backend's render_* series feed get_registry()) ride the same
        # heartbeat in daemon mode (one worker per process); colocated
        # harness workers pass their own fresh registries instead.
        self.metrics = metrics if metrics is not None else get_registry()
        self.span_tracer = span_tracer or Tracer(
            f"worker-{pm.worker_id_to_string(self.worker_id)}"
        )
        # Worker-end wire accounting + event-loop lag probe: the same
        # transport_*/obs_loop_* families the master exports, so both
        # ends of every exchange (and both loops) are priced.
        self._wire = WireAccounting(self.metrics)
        self.loopmon = LoopLagMonitor(
            self.metrics, role="worker", span_tracer=self.span_tracer
        )
        self.cancellation = CancellationToken()
        # Fault-injection seam: wraps every freshly-upgraded socket
        # (transport/faults.py FaultyConnection). None in production.
        self._connection_wrapper = connection_wrapper
        self._drain_requested = asyncio.Event()
        self._client: ReconnectingClient | None = None
        self._final_trace: WorkerTrace | None = None
        # Epoch of the master incarnation this worker last handshook with
        # (None until the first connect, and forever against epoch-less
        # masters). A reconnect that lands on a DIFFERENT epoch is a new
        # master: the worker re-announces fresh and drops stale queue
        # state instead of replaying it (PROTOCOL.md §Epoch fencing).
        self._master_epoch: int | None = None
        # Set when a RECONNECTING handshake was refused: the next attempt
        # announces first-connection (restarted epoch-less master).
        self._force_fresh_announce = False
        self._frame_queue: WorkerAutomaticQueue | None = None
        # Set by event_worker-migrate: after the drain-style goodbye, the
        # serve loop reconnects here instead of exiting (rebalancing).
        self._migrate_target: tuple[str, int] | None = None

    def _begin_fresh_session(self) -> None:
        """A reconnect landed on a NEW master incarnation (epoch change or
        refused reconnect): drop queue state belonging to the lost
        session. Anything still rendering finishes and is fenced by its
        old-epoch result; anything merely queued is work the new master
        will re-dispatch itself (its ledger knows what actually finished).
        """
        dropped = 0
        if self._frame_queue is not None:
            dropped = self._frame_queue.reset_session()
        self.metrics.counter(
            "worker_session_reannounces_total",
            "Reconnects that re-announced a fresh session to a new master "
            "incarnation (epoch change or refused reconnect)",
        ).inc()
        logger.info(
            "Fresh session with master (epoch %s); dropped %d stale "
            "queued frame(s).",
            self._master_epoch,
            dropped,
        )

    def request_drain(self) -> None:
        """Ask the worker to drain gracefully: finish the frame being
        rendered, return the rest of the queue via the goodbye message,
        and disconnect. Wired to SIGTERM by the CLI; safe to call from
        any task on the worker's loop, idempotent."""
        self._drain_requested.set()

    def _reset_for_rerun(self, host: str, port: int) -> None:
        """Point the worker at another master and refresh every per-run
        token so ``connect_and_run_to_job_completion`` can run again. The
        new master is a DIFFERENT incarnation by definition, so the next
        handshake announces a fresh first-connection session (the PR-11
        re-announce path — no change to the fencing contract)."""
        self.master_host = host
        self.master_port = port
        self.cancellation = CancellationToken()
        self._drain_requested = asyncio.Event()
        self._migrate_target = None
        self._master_epoch = None
        self._force_fresh_announce = True
        self._client = None
        self._final_trace = None

    async def connect_and_serve(
        self,
        route_fn: Callable[[], "asyncio.Future | object"] | None = None,
    ) -> WorkerTrace:
        """Run the job protocol, following migrations and router re-homes.

        Wraps :meth:`connect_and_run_to_job_completion` in a loop:

        - a run that ended because the master sent ``event_worker-migrate``
          reconnects to the migration target and keeps serving;
        - a run that DIED (connect retries exhausted — the shard's master
          is gone) asks the async ``route_fn`` for a new ``(host, port)``
          and re-homes there; without a ``route_fn`` (or when it returns
          None) the failure propagates exactly as before.

        Each hop re-announces a fresh session, so the receiving master
        sees an ordinary late-joining worker.
        """
        rehomes = 0
        while True:
            try:
                trace = await self.connect_and_run_to_job_completion()
            except (WebSocketClosed, ConnectionError, OSError, asyncio.TimeoutError):
                if route_fn is None:
                    raise
                target = await route_fn()
                if target is None or rehomes >= 16:
                    raise
                rehomes += 1
                host, port = target
                logger.info(
                    "Master %s:%d unreachable; re-homing to %s:%d (%d/16).",
                    self.master_host, self.master_port, host, port, rehomes,
                )
                self._reset_for_rerun(host, port)
                continue
            if self._migrate_target is not None:
                host, port = self._migrate_target
                logger.info(
                    "Migrating to %s:%d as requested by the master.", host, port
                )
                self._reset_for_rerun(host, port)
                continue
            return trace

    async def connect_and_run_to_job_completion(self) -> WorkerTrace:
        """Connect, serve the job protocol until job-finished, return the trace."""
        transport_metrics = TransportMetrics(self.metrics)

        async def fresh_connection(is_reconnect: bool) -> WebSocketConnection:
            with self.span_tracer.span(
                "reconnect" if is_reconnect else "connect",
                cat="transport",
                track="connection",
            ):
                ws = await connect_with_exponential_backoff(
                    self.master_host,
                    self.master_port,
                    metrics=transport_metrics,
                    wrap=self._connection_wrapper,
                )
                announce_reconnect = is_reconnect and not self._force_fresh_announce
                try:
                    epoch, fresh = await asyncio.wait_for(
                        _perform_handshake(
                            ws,
                            self.worker_id,
                            is_reconnect=announce_reconnect,
                            last_epoch=self._master_epoch,
                            wire=self._wire,
                        ),
                        HANDSHAKE_TIMEOUT,
                    )
                except ReconnectRefused:
                    # Retry (through the reconnect budget) with a fresh
                    # first-connection announce — the refusing master has
                    # no session to resume.
                    self._force_fresh_announce = True
                    ws.abort()
                    raise
                self._force_fresh_announce = False
                self._master_epoch = epoch
                if fresh and is_reconnect:
                    self._begin_fresh_session()
            return ws

        first = await fresh_connection(False)
        client = ReconnectingClient(
            first,
            lambda: fresh_connection(True),
            on_reconnect=self.tracer.trace_new_reconnect,
            metrics=transport_metrics,
        )
        self._client = client
        logger.info(
            "Worker %s connected to %s:%d",
            pm.worker_id_to_string(self.worker_id),
            self.master_host,
            self.master_port,
        )

        sender = SenderHandle(lambda m: client.send_text(self._wire.encode(m)))
        sender.start()
        self.loopmon.start()

        async def receive() -> pm.Message:
            return self._wire.decode(await client.receive_text())

        router = MessageRouter(receive)
        # Subscribe BEFORE the receive loop can dispatch: the master pings
        # immediately at registration (seeding its clock-offset estimator),
        # and an unsubscribed dispatch drops the message — the responder
        # task's own subscribe would run one scheduling pass too late.
        heartbeat_queue = router.subscribe(pm.MasterHeartbeatRequest)
        router.start()

        frame_queue = WorkerAutomaticQueue(
            self.backend,
            sender,
            self.tracer,
            self.cancellation,
            metrics=self.metrics,
            span_tracer=self.span_tracer,
        )
        self._frame_queue = frame_queue
        frame_queue.start()

        heartbeat_task = asyncio.create_task(
            self._respond_to_heartbeats(heartbeat_queue, sender),
            name="heartbeats",
        )
        try:
            await self._manage_incoming_messages(router, sender, frame_queue)
        finally:
            self.cancellation.cancel()
            heartbeat_task.cancel()
            await self.loopmon.stop()
            await frame_queue.join()
            await router.stop()
            await sender.stop()
            client.close()
        assert self._final_trace is not None
        return self._final_trace

    async def _respond_to_heartbeats(
        self, queue: asyncio.Queue, sender: SenderHandle
    ) -> None:
        """Answer pings; record every 8th as a ping trace.

        Reference: worker/src/connection/mod.rs:503-599. The queue is
        subscribed by the caller before the router starts, so the master's
        immediate first ping can never be dropped.
        """
        ping_counter = 0
        while True:
            request = await queue.get()
            received_at = time.time()
            # Every pong carries the compact metrics payload (the master
            # aggregates a live cluster-wide view with zero extra RPCs)
            # plus the worker-clock receive/respond timestamps that close
            # the NTP loop for the master's clock-offset estimator.
            await sender.send_message(
                pm.WorkerHeartbeatResponse(
                    metrics=self.metrics.to_wire(),
                    received_at=received_at,
                    responded_at=time.time(),
                    # Correlate pong to ping: with pong-miss retries on the
                    # master, an anonymous late pong could be mistaken for
                    # the retry's answer.
                    echo_request_time=request.request_time,
                )
            )
            ping_counter += 1
            if ping_counter % TRACE_EVERY_NTH_PING == 0:
                self.tracer.trace_new_ping(request.request_time, received_at)

    async def _manage_incoming_messages(
        self,
        router: MessageRouter,
        sender: SenderHandle,
        frame_queue: WorkerAutomaticQueue,
    ) -> None:
        """The select-loop over master requests/events.

        Reference: worker/src/connection/mod.rs:601-713.
        """
        add_queue = router.subscribe(pm.MasterFrameQueueAddRequest)
        remove_queue = router.subscribe(pm.MasterFrameQueueRemoveRequest)
        started_queue = router.subscribe(pm.MasterJobStartedEvent)
        finished_queue = router.subscribe(pm.MasterJobFinishedRequest)
        migrate_queue = router.subscribe(pm.MasterWorkerMigrateEvent)
        job_done = asyncio.Event()

        async def depart(reason: str) -> None:
            """Drain-style graceful departure: finish the in-flight frame,
            return the queued rest via the goodbye, close out the trace
            locally (no job-finished request will come for a departed
            worker), and end this run."""
            returned = await frame_queue.drain()
            job_name = returned[0][0] if returned else None
            await sender.send_message(
                pm.WorkerGoodbyeEvent(
                    reason=reason,
                    job_name=job_name,
                    returned_frames=tuple(
                        unit.frame_index for _, unit in returned
                    ),
                    returned_tiles=(
                        tuple(unit.tile for _, unit in returned)
                        if any(unit.tile is not None for _, unit in returned)
                        else None
                    ),
                )
            )
            logger.info(
                "Goodbye sent (%s, %d frame(s) returned); disconnecting.",
                reason,
                len(returned),
            )
            self.tracer.ensure_job_start_time(time.time())
            self.tracer.set_job_finish_time(time.time())
            self._final_trace = self.tracer.build()
            job_done.set()

        async def handle_adds() -> None:
            while True:
                request = await add_queue.get()
                if (
                    request.epoch is not None
                    and self._master_epoch is not None
                    and request.epoch != self._master_epoch
                ):
                    # A queue-add stamped with a different incarnation's
                    # epoch (a partitioned predecessor's socket flushing
                    # late): refuse and count, never silently enqueue.
                    self.metrics.counter(
                        "worker_stale_epoch_requests_total",
                        "Queue-add requests refused because their epoch "
                        "does not match the current master session",
                    ).inc()
                    await sender.send_message(
                        pm.WorkerFrameQueueAddResponse.new_errored(
                            request.message_request_id,
                            f"stale epoch {request.epoch} "
                            f"(current session epoch {self._master_epoch})",
                        )
                    )
                    continue
                try:
                    frame_queue.queue_frame(
                        request.job, request.frame_index, trace=request.trace,
                        job_id=request.job_id, tile=request.tile,
                        epoch=request.epoch,
                    )
                    self.tracer.increment_total_queued_frames()
                    response = pm.WorkerFrameQueueAddResponse.new_ok(
                        request.message_request_id
                    )
                except Exception as e:  # noqa: BLE001
                    response = pm.WorkerFrameQueueAddResponse.new_errored(
                        request.message_request_id, str(e)
                    )
                await sender.send_message(response)

        async def handle_removes() -> None:
            while True:
                request = await remove_queue.get()
                result = frame_queue.unqueue_frame(
                    request.job_name, request.frame_index, request.tile
                )
                if result == pm.FRAME_QUEUE_REMOVE_RESULT_REMOVED:
                    self.tracer.increment_total_frames_removed_from_queue()
                await sender.send_message(
                    pm.WorkerFrameQueueRemoveResponse.new_with_result(
                        request.message_request_id, result
                    )
                )

        async def handle_job_started() -> None:
            while True:
                event = await started_queue.get()
                logger.info(
                    "Job started%s.",
                    f" ({event.job_id})" if event.job_id is not None else "",
                )
                self.tracer.set_job_start_time(time.time())
                # Stamp the span timeline with the job's trace id (when the
                # master piggybacked one) so multi-job worker artifacts can
                # be partitioned by run; under the scheduler each announced
                # job also carries its submission id.
                args: dict | None = None
                if event.trace_id is not None:
                    args = {"trace_id": f"{event.trace_id:016x}"}
                if event.job_id is not None:
                    args = {**(args or {}), "job_id": event.job_id}
                self.span_tracer.instant(
                    "job started", cat="worker", track="job", args=args
                )

        async def handle_job_finished() -> None:
            request = await finished_queue.get()
            logger.info("Job finished; sending trace.")
            # A worker that never received event_job-started (an idle
            # shard drained before any job reached it) must still answer:
            # an unset start time would make build() raise, silently
            # killing this handler while the master waits out its 600 s
            # trace budget.
            self.tracer.ensure_job_start_time(time.time())
            self.tracer.set_job_finish_time(time.time())
            trace = self.tracer.build()
            self._final_trace = trace
            # Piggyback this worker's Chrome span timeline on the response:
            # every frame is finished by now, so the phase spans (and their
            # flow steps) are all recorded, and the master can assemble the
            # merged cluster timeline without another RPC.
            span_events = {
                "process_name": self.span_tracer.process_name,
                "events": self.span_tracer.metadata_events()
                + self.span_tracer.events(),
            }
            if self.span_tracer.dropped:
                # Truncation must stay visible across the wire: the master
                # records it in the merged document's otherData.
                span_events["dropped"] = self.span_tracer.dropped
            await sender.send_message(
                pm.WorkerJobFinishedResponse(
                    request.message_request_id, trace, span_events=span_events
                )
            )
            job_done.set()

        async def handle_drain() -> None:
            await self._drain_requested.wait()
            logger.info("Drain requested; finishing the in-flight frame.")
            await depart("drain")

        async def handle_migrate() -> None:
            event = await migrate_queue.get()
            logger.info(
                "Migrate requested (%s:%d%s); finishing the in-flight frame.",
                event.host,
                event.port,
                f", {event.reason}" if event.reason is not None else "",
            )
            # Record the target FIRST: the serve loop reads it after this
            # run unwinds to decide between exit and re-home.
            self._migrate_target = (event.host, event.port)
            self.metrics.counter(
                "worker_migrations_total",
                "Master-requested re-homes to another shard (rebalancing)",
            ).inc()
            await depart("migrate")

        tasks = [
            asyncio.create_task(handle_adds()),
            asyncio.create_task(handle_removes()),
            asyncio.create_task(handle_job_started()),
            asyncio.create_task(handle_job_finished()),
            asyncio.create_task(handle_drain()),
            asyncio.create_task(handle_migrate()),
        ]
        job_done_task = asyncio.create_task(job_done.wait())
        try:
            # Select on BOTH job completion and receive-loop death: when
            # the master is gone for good (the reconnect budget exhausted
            # inside the receive op), no job-finished event will ever set
            # ``job_done`` — the failure must propagate so the serve loop
            # (``connect_and_serve``) can ask the router for a new home
            # instead of parking this worker forever.
            await asyncio.wait(
                {job_done_task, router.dead},
                return_when=asyncio.FIRST_COMPLETED,
            )
            if not job_done.is_set():
                error = router.dead.result()
                if error is not None:
                    raise error
                raise WebSocketClosed(
                    "Receive loop ended before the job finished."
                )
        finally:
            job_done_task.cancel()
            for task in tasks:
                task.cancel()
            await asyncio.gather(
                job_done_task, *tasks, return_exceptions=True
            )
