"""Worker-side automatic render queue.

Reference: ``WorkerAutomaticQueue`` (worker/src/rendering/queue.rs:16-230) —
a 100 ms poll loop takes the first Queued frame, marks it Rendering, renders
one frame at a time, then emits the finished event and pops it.

Two deliberate deviations (reference bugs fixed — SURVEY.md §7):
- the ``event_frame-queue_item-started-rendering`` event IS emitted (the
  reference defines and handles it but never sends it, §3.3);
- a render failure emits ``event_frame-queue_item-finished`` with
  ``errored`` instead of silently dropping the frame (which would hang the
  reference master forever — worker/src/rendering/queue.rs:169-174).
"""

from __future__ import annotations

import asyncio
import enum
import logging
import time
from dataclasses import dataclass, field

from tpu_render_cluster.jobs.models import BlenderJob
from tpu_render_cluster.jobs.tiles import WorkUnit
from tpu_render_cluster.obs import MetricsRegistry, Tracer
from tpu_render_cluster.protocol import messages as pm
from tpu_render_cluster.transport.actors import SenderHandle
from tpu_render_cluster.traces.worker_trace import WorkerTraceBuilder
from tpu_render_cluster.utils.cancellation import CancellationToken
from tpu_render_cluster.worker.backends.base import RenderBackend

logger = logging.getLogger(__name__)

QUEUE_POLL_SECONDS = 0.1  # reference: worker/src/rendering/queue.rs:74-96

# The per-frame phase breakdown the paper's analysis is built around
# (reading/rendering/writing), plus the queue-wait the paper only derives
# post-hoc from trace gaps — here measured directly.
FRAME_PHASES = ("queue_wait", "read", "render", "write")


class FrameState(enum.Enum):
    QUEUED = "queued"
    RENDERING = "rendering"
    FINISHED = "finished"


@dataclass
class QueuedFrame:
    job: BlenderJob
    frame_index: int
    state: FrameState = FrameState.QUEUED
    queued_at: float = field(default_factory=time.time)
    # Trace context from the master's queue-add request (None from a
    # reference-shaped master); echoed on rendering/finished events and
    # routed through the phase spans as a Perfetto flow.
    trace: pm.TraceContext | None = None
    # Scheduler job id from the queue-add request (None from single-job
    # masters); echoed on rendering/finished events.
    job_id: str | None = None
    # Sub-frame tile index from the queue-add request (None = whole
    # frame); echoed on rendering/finished events.
    tile: int | None = None
    # Master epoch from the queue-add request (None from epoch-less
    # masters); echoed on rendering/finished events so a successor master
    # can fence out a predecessor's assignments after a failover.
    epoch: int | None = None
    # Worker-local session generation at queue time (see reset_session).
    session: int = 0

    @property
    def unit(self) -> WorkUnit:
        return WorkUnit(self.frame_index, self.tile)


class WorkerAutomaticQueue:
    """Serial render queue polled every 100 ms."""

    def __init__(
        self,
        backend: RenderBackend,
        sender: SenderHandle,
        tracer: WorkerTraceBuilder,
        cancellation: CancellationToken,
        *,
        metrics: MetricsRegistry | None = None,
        span_tracer: Tracer | None = None,
    ) -> None:
        self._backend = backend
        self._sender = sender
        self._tracer = tracer
        self._cancellation = cancellation
        self._metrics = metrics
        self._span_tracer = span_tracer
        self._phase_histogram = (
            metrics.histogram(
                "worker_frame_phase_seconds",
                "Per-frame phase durations (queue_wait/read/render/write)",
                labels=("phase",),
            )
            if metrics is not None
            else None
        )
        self._frames: list[QueuedFrame] = []
        self._finished_indices: set[tuple[str, int, int | None]] = set()
        # Bumped by reset_session(): a frame queued under a previous
        # master session that only finishes rendering AFTER the reset
        # must not re-enter the finished index (the new master may
        # legitimately re-assign that unit).
        self._session_generation = 0
        self._task: asyncio.Task | None = None
        self._draining = False
        # Wakes the render loop as soon as work arrives; the 100 ms sleep
        # remains only as a fallback poll (the reference burns up to a full
        # poll interval of idle time per queue refill — queue.rs:74-96).
        self._work_available = asyncio.Event()

    # -- queue interface (called from the message manager) -------------------

    def queue_frame(
        self,
        job: BlenderJob,
        frame_index: int,
        *,
        trace: pm.TraceContext | None = None,
        job_id: str | None = None,
        tile: int | None = None,
        epoch: int | None = None,
    ) -> None:
        if self._draining:
            # Refuse, don't silently park: the add RPC answers errored and
            # the master returns the frame to the pending pool — a frame
            # accepted here after drain() collected the queue would be lost.
            raise RuntimeError("Worker is draining; not accepting new frames.")
        self._frames.append(
            QueuedFrame(
                job, frame_index, trace=trace, job_id=job_id, tile=tile,
                epoch=epoch, session=self._session_generation,
            )
        )
        self._work_available.set()

    def unqueue_frame(
        self, job_name: str, frame_index: int, tile: int | None = None
    ) -> str:
        """Returns the frame-queue-remove result enum wire value.

        Reference: worker/src/rendering/queue.rs:192-229. ``tile`` rides
        the same optional piggyback as queue-add: a tiled steal removes
        exactly one tile, and whole-frame requests (tile None) only ever
        match whole-frame entries.
        """
        if (job_name, frame_index, tile) in self._finished_indices:
            return pm.FRAME_QUEUE_REMOVE_RESULT_ALREADY_FINISHED
        for i, frame in enumerate(self._frames):
            if (
                frame.job.job_name == job_name
                and frame.frame_index == frame_index
                and frame.tile == tile
            ):
                if frame.state is FrameState.RENDERING:
                    return pm.FRAME_QUEUE_REMOVE_RESULT_ALREADY_RENDERING
                if frame.state is FrameState.FINISHED:
                    return pm.FRAME_QUEUE_REMOVE_RESULT_ALREADY_FINISHED
                del self._frames[i]
                return pm.FRAME_QUEUE_REMOVE_RESULT_REMOVED
        return pm.FRAME_QUEUE_REMOVE_RESULT_ERRORED

    def queue_size(self) -> int:
        return len(self._frames)

    async def drain(self) -> list[tuple[str, int]]:
        """Graceful drain: finish the in-flight frame, hand back the rest.

        Stops the loop from starting new frames, waits for the one
        currently rendering to complete (its finished event goes out
        normally), and returns the ``(job_name, frame_index)`` pairs that
        never started — the payload of the goodbye message the runtime
        sends so the master can requeue them without waiting for a
        heartbeat-timeout eviction.
        """
        self._draining = True
        self._work_available.set()  # wake the loop so it parks promptly
        while any(f.state is FrameState.RENDERING for f in self._frames):
            await asyncio.sleep(0.01)
        returned = [
            (f.job.job_name, f.unit)
            for f in self._frames
            if f.state is FrameState.QUEUED
        ]
        self._frames = [f for f in self._frames if f.state is not FrameState.QUEUED]
        return returned

    def reset_session(self) -> int:
        """Drop the previous master session's queue state (failover).

        Called when the worker re-announces itself to a NEW master
        incarnation (epoch change / refused reconnect): the queued-but-
        not-started frames belong to assignments the new master does not
        know about, so replaying them would render work nobody tracks.
        The frame currently RENDERING is left to finish — its finished
        event carries the OLD epoch and the new master refuses it as
        stale, which is the fence working as designed. The already-
        finished index is cleared too: the new master may legitimately
        re-assign a unit this worker rendered for the predecessor, and an
        ``already-finished`` answer to a later remove RPC would lie about
        the NEW assignment. Returns how many queued frames were dropped.
        """
        dropped = [f for f in self._frames if f.state is FrameState.QUEUED]
        self._frames = [
            f for f in self._frames if f.state is not FrameState.QUEUED
        ]
        self._finished_indices.clear()
        # The frame left mid-RENDER belongs to the OLD session: when it
        # finishes, it must not re-enter the just-cleared finished index
        # (the generation check at insert time fences it out).
        self._session_generation += 1
        return len(dropped)

    # -- render loop ---------------------------------------------------------

    def start(self) -> None:
        self._task = asyncio.create_task(self._run(), name="render-queue")

    async def join(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass

    def _next_queued(self) -> QueuedFrame | None:
        for frame in self._frames:
            if frame.state is FrameState.QUEUED:
                return frame
        return None

    async def _run(self) -> None:
        while not self._cancellation.is_cancelled():
            frame = None if self._draining else self._next_queued()
            if frame is None:
                self._work_available.clear()
                try:
                    await asyncio.wait_for(
                        self._work_available.wait(), QUEUE_POLL_SECONDS
                    )
                except asyncio.TimeoutError:
                    pass
                continue
            await self._render_frame_and_report(frame)

    async def _render_frame_and_report(self, frame: QueuedFrame) -> None:
        frame.state = FrameState.RENDERING
        job_name = frame.job.job_name
        # Backends that batch internally (ray-pool mode) get the same-job
        # frames still queued HERE — real assigned work, so batching ahead
        # never renders a frame this worker doesn't own (see
        # RenderBackend's hint protocol).
        note_upcoming = getattr(self._backend, "note_upcoming_frames", None)
        if note_upcoming is not None:
            note_upcoming(
                frame.job,
                tuple(
                    f.unit
                    for f in self._frames
                    if f.state is FrameState.QUEUED
                    and f.job.job_name == job_name
                ),
            )
        await self._sender.send_message(
            pm.WorkerFrameQueueItemRenderingEvent(
                job_name, frame.frame_index, trace=frame.trace,
                job_id=frame.job_id, tile=frame.tile, epoch=frame.epoch,
            )
        )
        try:
            timing = await self._backend.render_frame(
                frame.job, frame.frame_index, tile=frame.tile
            )
        except Exception as e:  # noqa: BLE001 - report, don't hang the master
            logger.error("Unit %s render failed: %s", frame.unit.label, e)
            if self._metrics is not None:
                self._metrics.counter(
                    "worker_frames_errored_total", "Frames that failed to render"
                ).inc()
            # NOT added to _finished_indices: the master returns errored
            # frames to the pending pool and may re-queue them here; a later
            # remove request must not answer "already-finished".
            self._remove(frame)
            await self._sender.send_message(
                pm.WorkerFrameQueueItemFinishedEvent.new_errored(
                    job_name, frame.frame_index, str(e), trace=frame.trace,
                    job_id=frame.job_id, tile=frame.tile, epoch=frame.epoch,
                )
            )
            return
        self._tracer.trace_new_rendered_frame(frame.frame_index, timing)
        self._observe_frame_phases(frame, timing)
        self._remove(frame)
        if frame.session == self._session_generation:
            # A frame queued under a PREVIOUS master session (failover hit
            # while it rendered) stays out of the index: the new master
            # may re-assign this unit, and an "already-finished" answer to
            # a later remove RPC would lie about the NEW assignment.
            self._finished_indices.add(
                (job_name, frame.frame_index, frame.tile)
            )
        await self._sender.send_message(
            pm.WorkerFrameQueueItemFinishedEvent.new_ok(
                job_name, frame.frame_index, trace=frame.trace,
                job_id=frame.job_id, tile=frame.tile, epoch=frame.epoch,
            )
        )

    def _observe_frame_phases(self, frame: QueuedFrame, timing) -> None:
        """Feed the live per-phase histograms + emit retroactive spans.

        The spans reuse the 7-point wall-clock timestamps the backend
        already measured (the trace of record), so the Perfetto view and
        the legacy ``FrameRenderTime`` analysis agree exactly.
        """
        if self._metrics is None and self._span_tracer is None:
            return
        bounds = {
            "queue_wait": (frame.queued_at, timing.started_process_at),
            "read": (timing.started_process_at, timing.finished_loading_at),
            "render": (timing.started_rendering_at, timing.finished_rendering_at),
            "write": (timing.file_saving_started_at, timing.file_saving_finished_at),
        }
        for phase in FRAME_PHASES:
            start, end = bounds[phase]
            duration = max(0.0, end - start)
            if self._phase_histogram is not None:
                self._phase_histogram.observe(duration, phase=phase)
            if self._span_tracer is not None:
                args = {"frame": frame.frame_index}
                if frame.tile is not None:
                    args["tile"] = frame.tile
                if frame.trace is not None:
                    args["flow"] = frame.trace.flow_id
                self._span_tracer.complete(
                    phase,
                    cat="worker",
                    start_wall=start,
                    duration=duration,
                    track="frames",
                    args=args,
                )
                if frame.trace is not None:
                    # Route the assignment's flow through each phase span
                    # (mid-span so it binds even to zero-length phases):
                    # the master's assign span started it; its
                    # result-received span will terminate it.
                    flow_args = {"frame": frame.frame_index, "phase": phase}
                    if frame.tile is not None:
                        flow_args["tile"] = frame.tile
                    self._span_tracer.flow_step(
                        "frame",
                        id=frame.trace.flow_id,
                        ts=start + duration / 2.0,
                        cat="frame",
                        track="frames",
                        args=flow_args,
                    )
        if self._metrics is not None:
            self._metrics.counter(
                "worker_frames_rendered_total", "Frames rendered successfully"
            ).inc()

    def _remove(self, frame: QueuedFrame) -> None:
        if frame in self._frames:
            self._frames.remove(frame)
