"""Worker CLI entry point.

Flag surface matches the reference's clap parser (reference:
worker/src/cli.rs:5-45): ``worker --masterServerHost H --masterServerPort P
--baseDirectory D --blenderBinary B [-p prependArgs] [-a appendArgs]
[--logFilePath F]`` — plus the new ``--backend`` selector
(``blender`` | ``tpu-raytrace`` | ``mock``).
"""

from __future__ import annotations

import argparse
import asyncio
import signal
import sys
from pathlib import Path

from tpu_render_cluster.obs import (
    export_chrome_trace,
    get_tracer,
    write_metrics_snapshot,
)
from tpu_render_cluster.protocol import messages as pm
from tpu_render_cluster.utils.logging import initialize_console_and_file_logging
from tpu_render_cluster.utils.env import env_str
from tpu_render_cluster.worker.backends import create_backend
from tpu_render_cluster.worker.runtime import Worker


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="trc-worker", description="Render cluster worker")
    parser.add_argument("--masterServerHost", dest="master_host", required=True)
    parser.add_argument("--masterServerPort", dest="master_port", type=int, required=True)
    parser.add_argument("--baseDirectory", dest="base_directory", required=True)
    parser.add_argument("--blenderBinary", dest="blender_binary", default="blender")
    parser.add_argument("-p", "--blenderPrependArguments", dest="prepend_arguments", default=None)
    parser.add_argument("-a", "--blenderAppendArguments", dest="append_arguments", default=None)
    parser.add_argument("--logFilePath", dest="log_file_path", default=None)
    parser.add_argument(
        "--backend",
        choices=["blender", "tpu-raytrace", "mock"],
        default="blender",
        help="Render backend (default: blender, matching the reference).",
    )
    parser.add_argument(
        "--sharding",
        choices=["none", "tile", "spp"],
        default="none",
        help="tpu-raytrace only: split each frame across the local device "
        "mesh (tile = horizontal bands, spp = sample subsets psum-averaged "
        "over ICI; tpu_render_cluster/parallel/sharded_render.py).",
    )
    parser.add_argument(
        "--coordinatorAddress",
        dest="coordinator_address",
        default=None,
        help="tpu-raytrace only: join a multi-host JAX distributed runtime "
        "at this coordinator (host:port); with --numProcesses/--processId "
        "the worker's device mesh then spans hosts (DCN) as well as its "
        "local slice (ICI). Env fallbacks: JAX_COORDINATOR_ADDRESS / "
        "JAX_NUM_PROCESSES / JAX_PROCESS_ID.",
    )
    parser.add_argument(
        "--numProcesses", dest="num_processes", type=int, default=None,
    )
    parser.add_argument(
        "--processId", dest="process_id", type=int, default=None,
    )
    parser.add_argument(
        "--renderSize",
        dest="render_size",
        default="512x512",
        help="tpu-raytrace only: output WxH (default 512x512).",
    )
    parser.add_argument(
        "--renderSamples",
        dest="render_samples",
        type=int,
        default=8,
        help="tpu-raytrace only: samples per pixel (default 8).",
    )
    parser.add_argument(
        "--wavefront",
        choices=["auto", "off", "force"],
        default=None,
        help="tpu-raytrace only: wavefront execution (per-bounce active-ray "
        "compaction + bucketed relaunch; render/compaction.py). Default "
        "defers to the TRC_WAVEFRONT env tier; auto enables it for "
        "deep-walk mesh scenes where it measured faster.",
    )
    parser.add_argument(
        "--raypool",
        choices=["auto", "off", "force"],
        default=None,
        help="tpu-raytrace only: device-resident ray-pool execution "
        "(cross-frame wavefront batching with in-jit compaction; "
        "render/raypool.py). Default defers to the TRC_RAYPOOL env tier; "
        "auto enables it for multi-frame deep-walk mesh jobs, where the "
        "worker batches its queued frames into one pool internally (wire "
        "format unchanged). Takes precedence over --wavefront when both "
        "would fire.",
    )
    parser.add_argument(
        "--telemetryPort",
        dest="telemetry_port",
        type=int,
        default=None,
        help="Serve this worker's live metrics over HTTP on this port: "
        "/metrics (Prometheus text exposition) + /healthz. 0 picks an "
        "ephemeral port. Defaults to the TRC_OBS_WORKER_PORT environment "
        "variable; omit both to disable.",
    )
    parser.add_argument(
        "--telemetryHost",
        dest="telemetry_host",
        default="0.0.0.0",
        help="Bind address for the telemetry endpoints (default 0.0.0.0 so "
        "a remote Prometheus/dashboard can scrape the worker, matching "
        "the master's posture; use 127.0.0.1 to keep them local).",
    )
    parser.add_argument(
        "--router",
        default=None,
        help="host:port of the shard router's control endpoint. When set, "
        "a lost master does not end this worker: it asks the router's "
        "route_worker op for the least-loaded live shard and re-homes "
        "there (requires the router to be started with --shardWorkers). "
        "Master-requested migrations (rebalancing) are also followed.",
    )
    parser.add_argument(
        "--warmScene",
        dest="warm_scene",
        default=None,
        help="tpu-raytrace only: compile the renderer for this scene BEFORE "
        "connecting to the master, so the job window never contains XLA "
        "compilation (the analog of pre-pulling the Blender image).",
    )
    return parser


def make_backend(args: argparse.Namespace):
    if args.backend == "blender":
        return create_backend(
            "blender",
            blender_binary=args.blender_binary,
            base_directory=args.base_directory,
            prepend_arguments=args.prepend_arguments,
            append_arguments=args.append_arguments,
        )
    if args.backend == "tpu-raytrace":
        from tpu_render_cluster.parallel.mesh import initialize_multihost

        # Must happen before any other JAX use: afterwards jax.devices()
        # is the global (cross-host) set and sharded rendering spans DCN.
        initialize_multihost(
            args.coordinator_address, args.num_processes, args.process_id
        )
        cache_dir = env_str("TRC_COMPILE_CACHE")
        if cache_dir:
            # Persistent XLA compilation cache: the first worker process
            # pays the 20-40 s compile, later ones deserialize in ~1 s.
            try:
                import jax

                jax.config.update("jax_compilation_cache_dir", cache_dir)
                jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
                jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
            except Exception:  # noqa: BLE001 - cache is an optimization only
                pass
        try:
            width, height = (int(v) for v in args.render_size.lower().split("x"))
        except ValueError as e:
            raise SystemExit(f"--renderSize must be WxH: {e}")
        return create_backend(
            "tpu-raytrace",
            base_directory=args.base_directory,
            width=width,
            height=height,
            samples=args.render_samples,
            sharding=None if args.sharding == "none" else args.sharding,
            wavefront=args.wavefront,
            raypool=args.raypool,
        )
    return create_backend("mock")


ROUTE_ATTEMPTS = 10
ROUTE_RETRY_SECONDS = 0.25


def make_router_route_fn(router: str):
    """``route_fn`` for ``Worker.connect_and_serve``: ask the shard
    router where to (re)connect. A worker loses its master at exactly the
    moment the control plane is most likely to be churning (a shard died,
    maybe the router is restarting too), so the lookup retries for a few
    seconds before giving up; None (exit) only when the router stays
    unreachable or has no live shard to offer for the whole window."""
    host, _, port_text = router.rpartition(":")
    if not host:
        raise SystemExit(f"--router must be host:port, got {router!r}")
    port = int(port_text)

    async def route_fn() -> tuple[str, int] | None:
        from tpu_render_cluster.sched.control import control_request

        for attempt in range(ROUTE_ATTEMPTS):
            try:
                response = await control_request(
                    host, port, {"op": "route_worker"}, timeout=10.0
                )
            except (OSError, ValueError, ConnectionError, asyncio.TimeoutError):
                response = None
            if response is not None and response.get("ok"):
                return str(response["host"]), int(response["port"])
            if attempt + 1 < ROUTE_ATTEMPTS:
                await asyncio.sleep(ROUTE_RETRY_SECONDS)
        return None

    return route_fn


async def _run_worker(
    worker: Worker,
    telemetry_port: int | None = None,
    telemetry_host: str = "0.0.0.0",
    router: str | None = None,
):
    """Run to completion with SIGTERM wired to a graceful drain.

    A terminated worker daemon (node maintenance, preemption) finishes
    the frame it is rendering, returns its queue to the master via the
    goodbye message, and exits cleanly — instead of vanishing and making
    the master pay a heartbeat-timeout eviction to rediscover the frames.

    With ``telemetry_port`` set, the worker-local telemetry endpoints
    (/metrics + /healthz; obs/http.py) serve this daemon's registry live
    — the pull-based counterpart of the compact heartbeat piggyback the
    master aggregates.
    """
    loop = asyncio.get_running_loop()
    try:
        loop.add_signal_handler(signal.SIGTERM, worker.request_drain)
    except (NotImplementedError, RuntimeError):  # non-Unix loop
        pass
    telemetry = None
    history_sampler = None
    if telemetry_port is not None:
        from tpu_render_cluster.obs import HistorySampler, HistoryStore
        from tpu_render_cluster.obs.http import TelemetryServer

        # The worker's own metrics-history ring (obs/history.py): the
        # /history endpoint answers range/rate/quantile-over-window
        # queries so an operator (or the federated router) can see the
        # moments leading up to an incident on THIS daemon, not just the
        # cumulative /metrics snapshot.
        history = HistoryStore(worker.metrics)
        history_sampler = HistorySampler(history)
        history_sampler.start()
        telemetry = TelemetryServer(
            worker.metrics,
            host=telemetry_host,
            port=telemetry_port,
            healthz_fn=lambda: {
                "role": "worker",
                "worker_id": pm.worker_id_to_string(worker.worker_id),
                "backend": type(worker.backend).__name__,
            },
            history=history,
        )
        await telemetry.start()
    try:
        if router is not None:
            return await worker.connect_and_serve(make_router_route_fn(router))
        return await worker.connect_and_run_to_job_completion()
    finally:
        if telemetry is not None:
            await telemetry.stop()
        if history_sampler is not None:
            await history_sampler.stop()
        try:
            loop.remove_signal_handler(signal.SIGTERM)
        except (NotImplementedError, RuntimeError, ValueError):
            pass


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    initialize_console_and_file_logging(args.log_file_path)
    backend = make_backend(args)
    if args.warm_scene and args.backend == "tpu-raytrace":
        backend.warm(args.warm_scene)
    worker = Worker(args.master_host, args.master_port, backend)
    from tpu_render_cluster.obs.http import resolve_telemetry_port

    telemetry_port = resolve_telemetry_port(
        args.telemetry_port, "TRC_OBS_WORKER_PORT"
    )
    try:
        asyncio.run(
            _run_worker(worker, telemetry_port, args.telemetry_host, args.router)
        )
    finally:
        # Export this daemon's obs artifacts even when the run died (the
        # partial timeline matters most in exactly those runs): in
        # distributed mode the master only holds the compact heartbeat
        # payloads, so the worker's full span timeline (connect + per-frame
        # queue_wait/read/render/write) and registry live here. Filenames
        # match the master's artifact globs so analysis/run_all pointed at
        # (or above) this directory loads them.
        obs_directory = Path(args.base_directory) / "obs"
        worker_name = f"worker-{pm.worker_id_to_string(worker.worker_id)}"
        try:
            # The process-global tracer rides along: render-path spans (the
            # wavefront driver's per-bounce track) belong in the same file
            # as this worker's connection + frame-phase rows.
            export_chrome_trace(
                obs_directory / f"{worker_name}_trace-events.json",
                [worker.span_tracer, get_tracer()],
            )
            get_tracer().clear()
            # The roofline section (obs/profiling.py): per-kernel XLA
            # cost analysis paired with this worker's measured execute
            # times — the per-kernel achieved-vs-peak evidence the
            # statistics.json fold consumes.
            from tpu_render_cluster.obs.profiling import get_profiler

            roofline = get_profiler().view()
            write_metrics_snapshot(
                obs_directory / f"{worker_name}_metrics.json",
                worker.metrics,
                extra={"roofline": roofline} if roofline else None,
            )
        except Exception as e:  # noqa: BLE001 - obs must not mask the run error
            print(f"warning: obs artifact export failed: {e}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
