"""Worker CLI entry point.

Flag surface matches the reference's clap parser (reference:
worker/src/cli.rs:5-45): ``worker --masterServerHost H --masterServerPort P
--baseDirectory D --blenderBinary B [-p prependArgs] [-a appendArgs]
[--logFilePath F]`` — plus the new ``--backend`` selector
(``blender`` | ``tpu-raytrace`` | ``mock``).
"""

from __future__ import annotations

import argparse
import asyncio
import sys

from tpu_render_cluster.utils.logging import initialize_console_and_file_logging
from tpu_render_cluster.worker.backends import create_backend
from tpu_render_cluster.worker.runtime import Worker


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="trc-worker", description="Render cluster worker")
    parser.add_argument("--masterServerHost", dest="master_host", required=True)
    parser.add_argument("--masterServerPort", dest="master_port", type=int, required=True)
    parser.add_argument("--baseDirectory", dest="base_directory", required=True)
    parser.add_argument("--blenderBinary", dest="blender_binary", default="blender")
    parser.add_argument("-p", "--blenderPrependArguments", dest="prepend_arguments", default=None)
    parser.add_argument("-a", "--blenderAppendArguments", dest="append_arguments", default=None)
    parser.add_argument("--logFilePath", dest="log_file_path", default=None)
    parser.add_argument(
        "--backend",
        choices=["blender", "tpu-raytrace", "mock"],
        default="blender",
        help="Render backend (default: blender, matching the reference).",
    )
    return parser


def make_backend(args: argparse.Namespace):
    if args.backend == "blender":
        return create_backend(
            "blender",
            blender_binary=args.blender_binary,
            base_directory=args.base_directory,
            prepend_arguments=args.prepend_arguments,
            append_arguments=args.append_arguments,
        )
    if args.backend == "tpu-raytrace":
        return create_backend("tpu-raytrace", base_directory=args.base_directory)
    return create_backend("mock")


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    initialize_console_and_file_logging(args.log_file_path)
    backend = make_backend(args)
    worker = Worker(args.master_host, args.master_port, backend)
    asyncio.run(worker.connect_and_run_to_job_completion())
    return 0


if __name__ == "__main__":
    sys.exit(main())
